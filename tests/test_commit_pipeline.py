"""Pipelined-commit unit tests (ISSUE 3 tentpole).

Manager-level: the async vote lifecycle (issue → overlap → resolve),
veto/rollback bookkeeping, the speculation gates (healing replica never
speculates, errored latch, death-watch re-quorum mid-speculation), and
the misuse guards.

Trainer-level: bit-identical committed ``(params, opt_state)`` parity
between pipelined and sync mode over a schedule that includes a
group-wide veto (rollback + batch replay) and a mid-run data-plane
``PeerGoneError`` (the failed-op face of a peer dying) — the
fault-injection acceptance check.
"""

import hashlib
import threading
from datetime import timedelta
from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from torchft_tpu import telemetry
from torchft_tpu.collectives import CollectivesDummy, PeerGoneError
from torchft_tpu.coordination import QuorumResult
from torchft_tpu.manager import (
    MANAGER_ADDR_KEY,
    REPLICA_ID_KEY,
    Manager,
)
from torchft_tpu.store import StoreClient, StoreServer


def quorum_result(
    quorum_id=123,
    replica_rank=1,
    replica_world_size=2,
    heal=False,
    max_step=20,
    max_rank=None,
    max_world_size=2,
    recover_src_rank=None,
    recover_dst_ranks=(),
    participant_ids=(),
):
    q = QuorumResult()
    q.quorum_id = quorum_id
    q.replica_rank = replica_rank
    q.replica_world_size = replica_world_size
    q.recover_src_manager_address = "manager address"
    q.recover_src_rank = recover_src_rank
    q.recover_dst_ranks = list(recover_dst_ranks)
    q.store_address = "store_addr/prefix"
    q.max_step = max_step
    q.max_rank = max_rank
    q.max_world_size = max_world_size
    q.heal = heal
    q.participant_ids = list(participant_ids)
    return q


@pytest.fixture
def store_server():
    s = StoreServer()
    yield s
    s.shutdown()


class ManagerHarness:
    def __init__(self, store_server, collectives=None, **kwargs):
        self.store = StoreClient(store_server.address())
        self.store.set(MANAGER_ADDR_KEY, "dummy")
        self.store.set(REPLICA_ID_KEY, "dummy_id")
        self.collectives = collectives or CollectivesDummy(rank=0, world_size=1)
        self.load_state_dict = MagicMock()
        self.transport = MagicMock()
        self.transport.metadata.return_value = "transport_meta"
        # the striped heal path prefers recv_checkpoint_multi when the
        # transport has one (a MagicMock always does) — delegate to the
        # recv_checkpoint.return_value contract the tests configure
        self.transport.recv_checkpoint_multi.side_effect = (
            lambda *a, **k: self.transport.recv_checkpoint.return_value
        )
        kwargs.setdefault("min_replica_size", 2)
        kwargs.setdefault("timeout", timedelta(seconds=10))
        kwargs.setdefault("commit_pipeline", True)
        # patch stays active for the harness lifetime: the pipelined vote
        # path constructs a dedicated commit ManagerClient (and the
        # healing path one for the recovery source) — autospec returns the
        # same mock instance for every construction, so scripted votes on
        # self.client drive the async path too
        self._patcher = patch("torchft_tpu.manager.ManagerClient", autospec=True)
        self._patcher.start()
        self.manager = Manager(
            collectives=self.collectives,
            load_state_dict=self.load_state_dict,
            state_dict=lambda: {"user_key": 1},
            rank=1,
            world_size=2,
            store_addr=store_server.address(),
            checkpoint_transport=self.transport,
            **kwargs,
        )
        self.client = self.manager._client

    def shutdown(self):
        self.manager.shutdown(wait=False)
        self._patcher.stop()


@pytest.fixture
def harness(store_server):
    hs = []

    def make(**kwargs):
        h = ManagerHarness(store_server, **kwargs)
        hs.append(h)
        return h

    yield make
    for h in hs:
        h.shutdown()


def test_pipelined_happy_path(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    h.client.should_commit.return_value = True
    rollbacks0 = telemetry.COMMIT_PIPELINE_ROLLBACKS.value

    m.start_quorum()
    t = np.array([2.0, 4.0], dtype=np.float32)
    m.allreduce(t).wait()
    assert m.speculation_allowed()

    resolved = []
    fut = m.should_commit_async(on_resolved=resolved.append)
    assert m.pending_commit() is fut
    # issue-time disallow: the serving window closes before the overlap
    h.transport.disallow_checkpoint.assert_called_once()
    assert not m.speculation_allowed()  # at most one outstanding

    assert m.resolve_pending_commit() is True
    assert resolved == [True]
    assert m.pending_commit() is None
    assert m.current_step() == 1
    assert m.batches_committed() == 2
    assert telemetry.COMMIT_PIPELINE_ROLLBACKS.value == rollbacks0
    # vote went through the dedicated commit client (same mock object)
    h.client.should_commit.assert_called_once()


def test_veto_rolls_back(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    h.client.should_commit.return_value = False  # a peer rank vetoed
    rollbacks0 = telemetry.COMMIT_PIPELINE_ROLLBACKS.value

    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    resolved = []
    m.should_commit_async(on_resolved=resolved.append)
    assert m.resolve_pending_commit() is False
    assert resolved == [False]  # restore callback ran
    assert m.current_step() == 0  # nothing committed
    assert telemetry.COMMIT_PIPELINE_ROLLBACKS.value == rollbacks0 + 1
    events = telemetry.EVENTS.recent("commit_rollback")
    assert events and events[-1]["step"] == 0


def test_vote_rpc_failure_restores_and_raises(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    h.client.should_commit.side_effect = TimeoutError("vote lost")

    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    resolved = []
    m.should_commit_async(on_resolved=resolved.append)
    with pytest.raises(TimeoutError, match="vote lost"):
        m.resolve_pending_commit()
    # the step counts as not applied (sync parity): snapshot restored,
    # pending cleared so the manager is not wedged
    assert resolved == [False]
    assert m.pending_commit() is None
    assert m.current_step() == 0


def test_healing_replica_never_speculates(harness):
    h = harness(use_async_quorum=True)
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        heal=True, max_step=20, max_rank=None, recover_src_rank=0
    )
    h.transport.recv_checkpoint.return_value = {
        "user": {"recovered": True},
        "torchft": {"step": 20, "batches_committed": 40},
    }

    m.start_quorum()
    m.wait_quorum()
    assert m._healing
    assert not m.speculation_allowed()
    with pytest.raises(AssertionError, match="healing"):
        m.should_commit_async()
    # the sync path still works and lands the staged heal
    h.client.should_commit.side_effect = None
    h.client.should_commit.return_value = True
    assert m.should_commit()
    h.load_state_dict.assert_called_once_with({"recovered": True})
    assert m.current_step() == 21


def test_errored_latch_blocks_speculation_and_aborts_cleanly(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    # group decision echoes the local vote
    h.client.should_commit.side_effect = (
        lambda rank, step, vote, timeout=None, **kw: vote
    )

    # clean step k: speculate
    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    m.should_commit_async()

    # step k+1: an error latches DURING the speculative window
    m.start_quorum()
    m.report_error(RuntimeError("plane torn"))
    # the pending vote (snapshotted clean at issue time) still commits
    assert m.resolve_pending_commit() is True
    assert m.current_step() == 1
    # the CURRENT step is doomed: no speculation, sync vote aborts
    assert not m.speculation_allowed()
    assert not m.should_commit()
    assert m.current_step() == 1


def test_allreduce_guard_while_pending(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    h.client.should_commit.return_value = True

    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    m.should_commit_async()
    m.start_quorum()
    with pytest.raises(RuntimeError, match="resolve_pending_commit"):
        m.allreduce(np.ones(2, dtype=np.float32))
    m.resolve_pending_commit()


def test_should_commit_resolves_stray_pending(harness):
    # LocalSGD-style callers vote synchronously; a stray pending vote from
    # a mixed-paradigm caller is resolved first instead of wedging
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    h.client.should_commit.return_value = True

    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    m.should_commit_async()
    m.start_quorum()
    assert m.should_commit()  # resolves the pending vote, then votes
    assert m.pending_commit() is None
    assert m.current_step() == 2
    assert h.client.should_commit.call_count == 2


def test_deathwatch_requorum_mid_speculation_vetoes_step(harness):
    """A death-watch re-quorum lands while a vote is in flight: the
    pending vote (issue-time snapshot) commits untouched; the step whose
    ops then span two plane epochs is vetoed by the mixed-epoch guard."""
    h = harness(min_replica_size=1)
    m = h.manager
    h.client.should_commit.side_effect = (
        lambda rank, step, vote, timeout=None, **kw: vote
    )
    ids = ["replica_a", "replica_b"]
    h.client._quorum.side_effect = [
        quorum_result(quorum_id=123, max_rank=1, participant_ids=ids),
        # step 1's own quorum: same epoch (steady state) ...
        quorum_result(quorum_id=123, max_rank=1, participant_ids=ids),
        # ... then the death-watch early re-quorum delivers the shrink
        quorum_result(quorum_id=124, max_rank=1, participant_ids=["replica_a"]),
    ]

    # step 0: clean, speculate (vote rides a barrier we control so the
    # re-quorum demonstrably lands DURING the speculative window)
    gate = threading.Event()
    real_vote = h.client.should_commit.side_effect

    def gated_vote(rank, step, vote, timeout=None, **kw):
        gate.wait(5)
        return real_vote(rank, step, vote, timeout=timeout)

    h.client.should_commit.side_effect = gated_vote
    m.start_quorum()
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    m.should_commit_async()

    # step 1 begins; first op rides epoch 123
    m.start_quorum()
    m.wait_quorum()
    assert m._quorum_id == 123
    # ... vote still in flight; resolve before this step's collectives
    gate.set()
    assert m.resolve_pending_commit() is True
    assert m.current_step() == 1
    m.allreduce(np.ones(2, dtype=np.float32)).wait()

    # death watch: peer's socket died mid-step -> early re-quorum
    m._on_peer_death(1)
    m.wait_quorum()
    assert m._quorum_id == 124  # plane rebuilt under the doomed step
    # a later op of the SAME step rides the new epoch -> mixed
    m.allreduce(np.ones(2, dtype=np.float32)).wait()
    assert not m.speculation_allowed()
    assert not m.should_commit()
    assert m.current_step() == 1
    aborts = telemetry.EVENTS.recent("abort")
    assert aborts and aborts[-1]["mixed_epochs"] is True


def test_managed_optimizer_pipelined_rollback_replay(harness):
    import jax.numpy as jnp
    import optax

    from torchft_tpu.optim import ManagedOptimizer

    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    votes = {"n": 0}

    def vote_fn(rank, step, vote, timeout=None, **kw):
        votes["n"] += 1
        return vote and votes["n"] != 2  # veto the 2nd vote

    h.client.should_commit.side_effect = vote_fn

    opt = ManagedOptimizer(m, optax.sgd(0.1))
    opt.init({"w": jnp.ones(4, jnp.float32)})

    def grad_fn(params):
        return {"w": jnp.ones(4, jnp.float32)}

    for _ in range(4):
        opt.begin_step()
        grads = grad_fn(opt.params)
        opt.step(grads, grad_fn=grad_fn)
    opt.finish()

    assert opt.rollbacks == 1
    assert m.current_step() == 3  # 4 votes, one vetoed
    # sgd(0.1) on grads averaged over n=2 participants: 3 * 0.1 * 0.5
    np.testing.assert_allclose(
        np.asarray(opt.params["w"]), np.full(4, 0.85, np.float32), rtol=1e-6
    )


def test_heal_supersedes_pending_replay(harness):
    """A heal that lands after an out-of-band rollback must clear the
    sticky replay flag: the next step's gradients are computed on the
    healed (committed) state, so replaying/dropping them would lose a
    valid batch."""
    import jax.numpy as jnp
    import optax

    from torchft_tpu.optim import ManagedOptimizer

    h = harness()
    opt = ManagedOptimizer(h.manager, optax.sgd(0.1))
    opt.init({"w": jnp.ones(4, jnp.float32)})

    # an out-of-band resolution (e.g. LocalSGD.sync on a pipelined
    # manager) rolled a speculative step back...
    opt._replay_needed = True
    # ...then a heal installs committed state before the next step
    opt.load_state_dict(
        {"params": {"w": jnp.zeros(4, jnp.float32)}, "opt_state": opt._opt_state}
    )
    assert not opt._consume_replay()


def test_diloco_rejects_pipelined_manager(harness):
    import optax

    from torchft_tpu.local_sgd import DiLoCo

    h = harness(use_async_quorum=False)
    with pytest.raises(ValueError, match="commit_pipeline"):
        DiLoCo(h.manager, optax.sgd(0.1), sync_every=2)


# ---------------------------------------------------------------------------
# trainer parity: pipelined committed state is bit-identical to sync mode
# ---------------------------------------------------------------------------


class FaultyDummy(CollectivesDummy):
    """CollectivesDummy that raises PeerGoneError on scripted allreduce
    calls — the failed-op face of a peer dying mid-step."""

    def __init__(self, fault_calls, **kwargs):
        super().__init__(**kwargs)
        self.fault_calls = set(fault_calls)
        self.calls = 0

    def allreduce(self, arrays, op=None):
        self.calls += 1
        if self.calls in self.fault_calls:
            raise PeerGoneError(0, f"peer 0 died mid-op (call {self.calls})")
        return super().allreduce(arrays)


def _tree_checksum(tree) -> str:
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


class TestTrainerParity:
    STEPS = 5
    VETO_VOTES = {2}  # 1-based vote index vetoed group-wide
    FAULT_CALLS = {4}  # 1-based backend-allreduce index that dies

    @pytest.fixture(scope="class")
    def train_step(self):
        import jax.numpy as jnp
        import optax

        from torchft_tpu.models.transformer import TransformerConfig
        from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
        from torchft_tpu.parallel.train_step import TrainStep

        cfg = TransformerConfig(
            vocab_size=32,
            d_model=16,
            n_layers=1,
            n_heads=2,
            head_dim=8,
            d_ff=32,
            dtype=jnp.float32,
        )
        # one shared TrainStep: both variants reuse the same jit caches
        # (identical compiled programs — any state divergence is real)
        return TrainStep(cfg, optax.adam(1e-2), make_mesh(MeshConfig(dp=1)))

    def _run(self, store_server, train_step, pipelined: bool):
        import jax
        import jax.numpy as jnp

        from torchft_tpu.parallel.ft import FTTrainer

        h = ManagerHarness(
            store_server,
            collectives=FaultyDummy(
                self.FAULT_CALLS, rank=0, world_size=1
            ),
            commit_pipeline=pipelined,
        )
        try:
            m = h.manager
            h.client._quorum.return_value = quorum_result(max_rank=1)
            votes = {"n": 0}

            def vote_fn(rank, step, vote, timeout=None, **kw):
                votes["n"] += 1
                return vote and votes["n"] not in self.VETO_VOTES

            h.client.should_commit.side_effect = vote_fn

            trainer = FTTrainer(m, train_step)
            trainer.init(jax.random.PRNGKey(0))
            data_rng = np.random.default_rng(7)
            batches = [
                jnp.asarray(
                    data_rng.integers(0, 32, (2, 4)), jnp.int32
                )
                for _ in range(self.STEPS)
            ]
            for tokens in batches:
                trainer.step(tokens)
            if pipelined:
                trainer.finish()
            return (
                _tree_checksum(trainer.params),
                _tree_checksum(trainer.opt_state),
                m.current_step(),
                votes["n"],
                trainer.rollbacks,
            )
        finally:
            h.shutdown()

    def test_committed_state_bit_identical(self, store_server, train_step):
        """Veto (rollback + replay) and a mid-run PeerGoneError leave the
        pipelined run's committed (params, opt_state) checksums exactly
        equal to sync mode's — the fault-injection acceptance check."""
        p_params, p_opt, p_step, p_votes, p_rb = self._run(
            store_server, train_step, pipelined=True
        )
        s_params, s_opt, s_step, s_votes, s_rb = self._run(
            store_server, train_step, pipelined=False
        )
        assert p_votes == s_votes == self.STEPS  # one vote per step
        assert p_step == s_step == self.STEPS - len(
            self.VETO_VOTES | self.FAULT_CALLS
        )
        assert p_rb >= 1 and s_rb == 0  # the veto really exercised rollback
        assert p_params == s_params
        assert p_opt == s_opt

    def test_heal_supersedes_pending_replay(self, store_server, train_step):
        """FTTrainer.load_state_dict (the heal path) must clear both the
        snapshot AND the sticky replay flag — see the ManagedOptimizer
        twin above."""
        import jax

        from torchft_tpu.parallel.ft import FTTrainer

        h = ManagerHarness(store_server, commit_pipeline=True)
        try:
            trainer = FTTrainer(h.manager, train_step)
            trainer.init(jax.random.PRNGKey(0))
            trainer._replay_needed = True
            trainer._snapshot = (trainer.params, trainer.opt_state)
            trainer.load_state_dict(
                {"params": trainer.params, "opt_state": trainer.opt_state}
            )
            assert trainer._snapshot is None
            assert not trainer._consume_replay()
        finally:
            h.shutdown()
