"""Subprocess-isolated collectives tests (Baby PG tests analogue:
process_group_test.py:346-397, multiprocessing_test.py)."""

import multiprocessing as mp
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.collectives import CollectivesTcp, ReduceOp
from torchft_tpu.multiprocessing import MonitoredQueue
from torchft_tpu.proxy import CollectivesProxy
from torchft_tpu.store import StoreServer


def make_tcp_backend():
    return CollectivesTcp(timeout=timedelta(seconds=10))


class TestMonitoredQueue:
    def test_dead_process_detection(self):
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        proc = ctx.Process(target=time.sleep, args=(0.2,))
        proc.start()
        proc.join()
        mq = MonitoredQueue(q)
        with pytest.raises(RuntimeError, match="dead"):
            mq.get(proc, timeout=5.0)

    def test_exception_reraise(self):
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        proc = ctx.Process(target=time.sleep, args=(5,))
        proc.start()
        try:
            q.put(ValueError("boom"))
            mq = MonitoredQueue(q)
            with pytest.raises(ValueError, match="boom"):
                mq.get(proc, timeout=5.0)
        finally:
            proc.terminate()
            proc.join()


@pytest.fixture
def proxy_pair():
    store = StoreServer()
    proxies = [
        CollectivesProxy(make_tcp_backend, timeout=timedelta(seconds=20))
        for _ in range(2)
    ]
    with ThreadPoolExecutor(max_workers=2) as pool:
        list(pool.map(lambda i: proxies[i].configure(store.address(), i, 2), range(2)))
    yield proxies
    for p in proxies:
        p.shutdown()
    store.shutdown()


class TestCollectivesProxy:
    def test_plane_info_reports_inner_backend(self, proxy_pair):
        """The proxy labels the child's LIVE transport (proxy:<inner>), so
        a silent CMA->TCP fallback stays visible on the dashboard even
        under the kill-safe deployment (ADVICE r5 #2)."""
        for p in proxy_pair:
            info = p.plane_info()
            assert info.startswith("proxy:") and len(info) > len("proxy:"), info
            # the inner label is the TCP backend's routing, not a class name
            assert "CollectivesTcp" not in info

    def test_allreduce_shm_path(self, proxy_pair):
        """Buckets above the shm threshold ride shared memory (one copy
        each way, no pickle) and still land in-place in caller buffers —
        the reference's _maybe_share_tensors (process_group.py:775-786)."""
        import glob

        n = 1 << 16  # 256 KB of f32 — well over _SHM_MIN_BYTES
        a = np.full(n, 1.0, dtype=np.float32)
        b = np.full(n, 2.0, dtype=np.float32)
        # only python shm segments (psm_*) count; other processes' /dev/shm
        # churn (semaphores etc.) must not flake this
        before = set(glob.glob("/dev/shm/psm_*"))
        w0 = proxy_pair[0].allreduce([a], ReduceOp.SUM)
        w1 = proxy_pair[1].allreduce([b], ReduceOp.SUM)
        w0.wait(timeout=timedelta(seconds=20))
        w1.wait(timeout=timedelta(seconds=20))
        np.testing.assert_array_equal(a, np.full(n, 3.0, np.float32))
        np.testing.assert_array_equal(b, np.full(n, 3.0, np.float32))
        # segments are unlinked after copy-back (no /dev/shm leak); poll
        # briefly in case another local test's segment is mid-flight
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            leaked = set(glob.glob("/dev/shm/psm_*")) - before
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked, leaked

    def test_allreduce_shm_bfloat16(self, proxy_pair):
        import ml_dtypes

        n = 1 << 16
        a = np.full(n, 1.0, dtype=ml_dtypes.bfloat16)
        b = np.full(n, 2.0, dtype=ml_dtypes.bfloat16)
        w0 = proxy_pair[0].allreduce([a], ReduceOp.AVG)
        w1 = proxy_pair[1].allreduce([b], ReduceOp.AVG)
        w0.wait(timeout=timedelta(seconds=20))
        w1.wait(timeout=timedelta(seconds=20))
        np.testing.assert_array_equal(a.astype(np.float32), 1.5)
        np.testing.assert_array_equal(b.astype(np.float32), 1.5)

    def test_allreduce_in_place(self, proxy_pair):
        a = np.array([1.0, 2.0], dtype=np.float32)
        b = np.array([3.0, 4.0], dtype=np.float32)
        w0 = proxy_pair[0].allreduce([a], ReduceOp.SUM)
        w1 = proxy_pair[1].allreduce([b], ReduceOp.SUM)
        w0.wait(timeout=timedelta(seconds=20))
        w1.wait(timeout=timedelta(seconds=20))
        np.testing.assert_allclose(a, [4.0, 6.0])  # caller buffer mutated
        np.testing.assert_allclose(b, [4.0, 6.0])

    def test_child_kill_surfaces_quickly(self, proxy_pair):
        proxy_pair[0].kill_child()
        t0 = time.monotonic()
        w = proxy_pair[0].allreduce(
            [np.ones(2, dtype=np.float32)], ReduceOp.SUM
        )
        with pytest.raises(Exception):
            w.wait(timeout=timedelta(seconds=10))
        assert time.monotonic() - t0 < 5.0

    def test_manager_over_proxy_kill_child_recovers_without_restart(self):
        """The Baby-PG story end to end (round-1 review 'what's weak' #2):
        Manager drives subprocess-isolated collectives; a SIGKILLed child
        mid-run latches an error, the failed commit requests a data-plane
        flush, the next quorum bumps quorum_id for BOTH groups, configure()
        respawns the child, and training recovers to identical states —
        no trainer process/thread restart involved."""
        from torchft_tpu.coordination import LighthouseServer
        from torchft_tpu.manager import Manager
        from torchft_tpu.optim import ManagedOptimizer

        from tests.test_integration import _init_params, _loss_fn

        import jax
        import optax

        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        stores = [StoreServer() for _ in range(2)]
        kill_once = {"done": False}
        total_steps = 4

        def loop(gid):
            manager = Manager(
                collectives=CollectivesProxy(
                    make_tcp_backend, timeout=timedelta(seconds=20)
                ),
                load_state_dict=None,
                state_dict=None,
                min_replica_size=2,
                replica_id=str(gid),
                store_addr=stores[gid].address(),
                rank=0,
                world_size=1,
                lighthouse_addr=lighthouse.address(),
                timeout=timedelta(seconds=15),
                quorum_timeout=timedelta(seconds=30),
            )
            try:
                opt = ManagedOptimizer(manager, optax.sgd(0.05))
                opt.init(_init_params())
                grad_fn = jax.jit(jax.grad(_loss_fn))
                rng = np.random.default_rng(77 + gid)
                commits = []
                for _ in range(40):
                    opt.begin_step()
                    x = rng.standard_normal((8, 3)).astype(np.float32)
                    y = rng.standard_normal((8, 4)).astype(np.float32)
                    if (
                        gid == 1
                        and manager.current_step() == 2
                        and not kill_once["done"]
                    ):
                        kill_once["done"] = True
                        manager._collectives.kill_child()
                    grads = grad_fn(opt.params, x, y)
                    before = manager.current_step()
                    opt.step(grads)
                    commits.append(manager.current_step() > before)
                    if manager.current_step() >= total_steps:
                        break
                return {
                    "params": jax.tree_util.tree_map(np.asarray, opt.params),
                    "commits": commits,
                    "step": manager.current_step(),
                }
            finally:
                manager.shutdown(wait=False)

        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                a, b = list(ex.map(loop, range(2)))
        finally:
            for s in stores:
                s.shutdown()
            lighthouse.shutdown()

        assert a["step"] >= total_steps and b["step"] >= total_steps
        # the killed-child step must NOT have committed on either group...
        assert False in a["commits"] and False in b["commits"]
        # ...and both groups converge to bit-identical params afterwards
        for key in a["params"]:
            np.testing.assert_array_equal(a["params"][key], b["params"][key])

    def test_reconfigure_respawns(self, proxy_pair):
        store2 = StoreServer()
        try:
            proxy_pair[0].kill_child()
            old_pids = [p._proc.pid for p in proxy_pair]
            with ThreadPoolExecutor(max_workers=2) as pool:
                list(
                    pool.map(
                        lambda i: proxy_pair[i].configure(store2.address(), i, 2),
                        range(2),
                    )
                )
            assert [p._proc.pid for p in proxy_pair] != old_pids
            a = np.ones(4, dtype=np.float32)
            b = np.ones(4, dtype=np.float32)
            w0 = proxy_pair[0].allreduce([a], ReduceOp.AVG)
            w1 = proxy_pair[1].allreduce([b], ReduceOp.AVG)
            w0.wait(timeout=timedelta(seconds=20))
            w1.wait(timeout=timedelta(seconds=20))
            np.testing.assert_allclose(a, 1.0)
        finally:
            store2.shutdown()
