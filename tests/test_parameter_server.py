"""Parameter-server prototype tests (reference has none for
parameter_server.py — this adds coverage the reference lacks)."""

from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.collectives import Collectives, CollectivesTcp
from torchft_tpu.parameter_server import ParameterServer


class DoublingPS(ParameterServer):
    """Echo server: per session, receive arrays, send back 2x, until the
    client hangs up."""

    @classmethod
    def new_collectives(cls) -> Collectives:
        return CollectivesTcp(timeout=timedelta(seconds=10))

    def forward(self, session_id: str, coll: Collectives) -> None:
        while True:
            buf = np.zeros(4, dtype=np.float32)
            coll.recv(buf, src=1, tag=1).wait(timedelta(seconds=10))
            coll.send(buf * 2, dst=1, tag=2).wait(timedelta(seconds=10))


def test_sessions_and_recovery():
    ps = DoublingPS()
    try:
        # session 1
        client = DoublingPS.new_session(ps.address())
        x = np.arange(4, dtype=np.float32)
        client.send(x, dst=0, tag=1).wait(timedelta(seconds=10))
        out = np.zeros(4, dtype=np.float32)
        client.recv(out, src=0, tag=2).wait(timedelta(seconds=10))
        np.testing.assert_allclose(out, x * 2)

        # client "dies" (session dropped); a new session works — the PS
        # needs no global coordination to recover
        client.shutdown()
        client2 = DoublingPS.new_session(ps.address())
        client2.send(x + 1, dst=0, tag=1).wait(timedelta(seconds=10))
        out2 = np.zeros(4, dtype=np.float32)
        client2.recv(out2, src=0, tag=2).wait(timedelta(seconds=10))
        np.testing.assert_allclose(out2, (x + 1) * 2)
        client2.shutdown()
    finally:
        ps.shutdown()
