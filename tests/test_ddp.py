"""DDP bucketing / pipeline unit tests (torchft/ddp.py:32-71 analogue;
the per-bucket schedule is what the round-3 host pipeline rides)."""

import numpy as np
import pytest

from torchft_tpu.ddp import flatten_buckets, plan_buckets, unflatten_buckets


def test_plan_respects_bucket_bytes_and_dtype():
    meta = [
        (np.dtype(np.float32), 60),
        (np.dtype(np.float32), 60),   # fits with first under 128
        (np.dtype(np.float32), 60),   # overflows -> new bucket
        (np.dtype(np.float16), 10),   # dtype change -> new bucket
        (np.dtype(np.float16), 10),
    ]
    plan = plan_buckets(meta, bucket_bytes=128)
    assert plan == [[0, 1], [2], [3, 4]]


def test_plan_empty_and_oversized():
    assert plan_buckets([], bucket_bytes=128) == []
    # a single leaf larger than the bucket still gets its own bucket
    assert plan_buckets([(np.dtype(np.float32), 10**9)], 128) == [[0]]


def test_flatten_unflatten_roundtrip():
    rng = np.random.default_rng(0)
    leaves = [
        rng.standard_normal(13).astype(np.float32),
        rng.standard_normal((3, 5)).astype(np.float32),
        rng.standard_normal(7).astype(np.float16),
        np.float32(rng.standard_normal()).reshape(()),  # scalar leaf
    ]
    buckets = flatten_buckets(leaves, bucket_bytes=64)
    # every element lands in exactly one bucket
    total = sum(buf.size for buf, _ in buckets)
    assert total == sum(l.size for l in leaves)
    out = unflatten_buckets(buckets, leaves)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(a, np.asarray(b))
        assert b.shape == a.shape and b.dtype == a.dtype


def test_pipeline_issues_one_managed_op_per_bucket():
    """The host path must submit buckets as separate managed ops (that is
    the pipelining) and reassemble exact averages."""
    import jax.numpy as jnp

    from torchft_tpu.ddp import allreduce_gradients
    from torchft_tpu.futures import Future

    calls = []

    class ManagerStub:
        def device_data_plane(self):
            return False

        def allreduce_many(self, tensors):
            calls.append([t.copy() for t in tensors])
            for t in tensors:
                np.divide(t, 1.0, out=t)  # identity "average", world 1
            return Future.completed(tensors)

    grads = {f"g{i}": jnp.full((16,), float(i)) for i in range(5)}
    out = allreduce_gradients(ManagerStub(), grads, bucket_bytes=64)
    assert len(calls) == 5  # one op per bucket at 64B buckets
    for i in range(5):
        np.testing.assert_allclose(np.asarray(out[f"g{i}"]), float(i))


# The mid-pipeline data-plane-death path (error latch + default-resolving
# futures + commit veto) runs against a REAL Manager in
# tests/test_manager.py::test_pipelined_averaging_latches_midway_error.
