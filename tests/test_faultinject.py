"""Fault-injection plane tests.

Fast tier: schedule determinism (same seed → identical fired-site
sequence), nth/every/p matching, action semantics, torn-write framing at
the wire layer, and a 2-replica in-process integration run injecting one
``commit.vote`` delay + one ``rpc.recv`` error — the multi-process
scenario matrix lives behind ``-m faultmatrix`` (and in
``python -m torchft_tpu.faultinject.runner``); see
``docs/fault_injection.md``.
"""

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu import telemetry
from torchft_tpu.collectives import CollectivesTcp, PeerGoneError
from torchft_tpu.faultinject import core as fi
from torchft_tpu.store import StoreServer


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with no schedule installed."""
    fi.configure(None)
    yield
    fi.configure(None)


@pytest.fixture()
def store():
    s = StoreServer()
    yield s
    s.shutdown()


def _drive(plane_schedule, script):
    """Install ``plane_schedule`` fresh and replay ``script`` — a list of
    (site, match) occurrences — swallowing injected errors; returns the
    plane's fired sequence."""
    plane = fi.configure(plane_schedule)
    for site, match in script:
        try:
            fi.fault_point(site, match=match)
        except Exception:  # noqa: BLE001 — injected errors are the point
            pass
    return plane.fired_sequence()


class TestScheduleEngine:
    SCHEDULE = {
        "seed": 7,
        "rules": [
            {"site": "rpc.recv", "nth": 3, "action": "error",
             "exc": "ConnectionError"},
            {"site": "collective.issue", "match": "allreduce",
             "every": 4, "action": "delay", "ms": 0},
            {"site": "cma.pull", "p": 0.25, "action": "error",
             "exc": "OSError", "limit": 0},
        ],
    }

    def _script(self):
        script = []
        for i in range(200):
            script.append(("rpc.recv", f"peer{i % 2}"))
            script.append(
                ("collective.issue",
                 "allreduce" if i % 3 else "broadcast")
            )
            script.append(("cma.pull", f"pid{1000 + i}"))
        return script

    def test_same_seed_replays_identical_sequence(self):
        """THE determinism contract: a fixed seed replays the identical
        (site, match, action, hit) firing sequence."""
        first = _drive(self.SCHEDULE, self._script())
        second = _drive(self.SCHEDULE, self._script())
        assert first, "schedule never fired — the test proves nothing"
        assert first == second
        # and the probabilistic rule actually participated
        assert any(site == "cma.pull" for site, *_ in first)

    def test_different_seed_changes_probabilistic_fires(self):
        reseeded = dict(self.SCHEDULE, seed=8)
        a = _drive(self.SCHEDULE, self._script())
        b = _drive(reseeded, self._script())
        a_p = [r for r in a if r[0] == "cma.pull"]
        b_p = [r for r in b if r[0] == "cma.pull"]
        assert a_p != b_p, "200 Bernoulli(0.25) draws agreed across seeds"

    def test_nth_fires_exactly_once_on_nth_occurrence(self):
        plane = fi.configure(
            {"rules": [{"site": "rpc.send", "nth": 3, "action": "delay",
                        "ms": 0}]}
        )
        fires = []
        for i in range(10):
            inj = fi.fault_point("rpc.send", match="x", wire=True)
            fires.append((i, inj is not None))
        assert [i for i, fired in fires if fired] == [2]  # 3rd occurrence
        assert len(plane.fired_sequence()) == 1

    def test_every_and_limit(self):
        fi.configure(
            {"rules": [{"site": "rpc.send", "every": 2, "limit": 2,
                        "action": "delay", "ms": 0}]}
        )
        fired = [
            fi.fault_point("rpc.send", wire=True) is not None
            for _ in range(10)
        ]
        assert fired == [False, True, False, True] + [False] * 6

    def test_match_is_substring_filter(self):
        fi.configure(
            {"rules": [{"site": "collective.issue", "match": "allreduce",
                        "nth": 1, "action": "delay", "ms": 0}]}
        )
        assert fi.fault_point("collective.issue", match="broadcast") is None
        assert (
            fi.fault_point("collective.issue", match="proxy.allreduce")
            is not None
        )

    def test_unknown_site_and_action_rejected(self):
        with pytest.raises(ValueError, match="unknown injection site"):
            fi.configure({"rules": [{"site": "nope", "action": "drop"}]})
        with pytest.raises(ValueError, match="unknown action"):
            fi.configure({"rules": [{"site": "rpc.send", "action": "zap"}]})

    def test_error_action_raises_configured_class(self):
        fi.configure(
            {"rules": [{"site": "quorum.reply", "nth": 1, "action": "error",
                        "exc": "TimeoutError", "msg": "synthetic"}]}
        )
        with pytest.raises(TimeoutError, match="fault injection: quorum"):
            fi.fault_point("quorum.reply")

    def test_drop_degrades_to_error_at_non_wire_site(self):
        """A schedule must never silently no-op: drop/torn at a site that
        can't implement them raises instead."""
        fi.configure(
            {"rules": [{"site": "commit.vote", "nth": 1, "action": "drop"}]}
        )
        with pytest.raises(ConnectionError):
            fi.fault_point("commit.vote", match="rpc")

    def test_delay_action_sleeps(self):
        fi.configure(
            {"rules": [{"site": "ckpt.recv", "nth": 1, "action": "delay",
                        "ms": 80}]}
        )
        t0 = time.perf_counter()
        fi.fault_point("ckpt.recv")
        assert time.perf_counter() - t0 >= 0.07

    def test_env_schedule_inline_and_file(self, tmp_path, monkeypatch):
        doc = {"rules": [{"site": "rpc.send", "nth": 1, "action": "drop"}]}
        monkeypatch.setenv(fi.ENV_SCHEDULE, json.dumps(doc))
        fi._PLANE = fi._UNSET  # force the lazy env load
        plane = fi.active()
        assert plane is not None and len(plane.rules) == 1
        p = tmp_path / "sched.json"
        p.write_text(json.dumps(doc))
        monkeypatch.setenv(fi.ENV_SCHEDULE, f"@{p}")
        fi._PLANE = fi._UNSET
        plane = fi.active()
        assert plane is not None and plane.rules[0].site == "rpc.send"

    def test_malformed_env_schedule_disables_not_crashes(self, monkeypatch):
        monkeypatch.setenv(fi.ENV_SCHEDULE, "{not json")
        fi._PLANE = fi._UNSET
        assert fi.active() is None

    def test_kill_writes_evidence_before_signal(self, tmp_path, monkeypatch):
        """sig=0 is a liveness probe — the kill path runs end to end
        (evidence written, os.kill invoked) without dying."""
        monkeypatch.setenv(fi.ENV_EVIDENCE_DIR, str(tmp_path))
        fi.configure(
            {"rules": [{"site": "collective.issue", "nth": 1,
                        "action": "kill", "sig": 0}]}
        )
        inj = fi.fault_point("collective.issue", match="allreduce")
        assert inj is not None and inj.action == "kill"
        recs = fi.read_evidence(str(tmp_path))
        assert len(recs) == 1
        assert recs[0]["site"] == "collective.issue"
        assert recs[0]["action"] == "kill"
        assert recs[0]["pid"] == os.getpid()
        # ... and conftest's policy treats it as an injected death
        from conftest import injected_kill_evidence

        assert injected_kill_evidence(str(tmp_path))

    def test_fired_injection_lands_in_telemetry(self):
        telemetry.EVENTS.clear()
        before = telemetry.FAULTS_INJECTED.labels(
            site="rpc.recv", action="delay"
        ).value
        fi.configure(
            {"rules": [{"site": "rpc.recv", "nth": 1, "action": "delay",
                        "ms": 0}]}
        )
        fi.fault_point("rpc.recv", match="peer1")
        assert (
            telemetry.FAULTS_INJECTED.labels(
                site="rpc.recv", action="delay"
            ).value
            == before + 1
        )
        events = telemetry.EVENTS.recent("fault_injected")
        assert events and events[-1]["site"] == "rpc.recv"
        assert events[-1]["hit"] == 1
        # flight recorder carries the forensic entry
        ops = [r["op"] for r in telemetry.FLIGHT.snapshot()]
        assert "fault.delay" in ops


class TestWireTorn:
    """Torn-write framing at the wire layer: the receiver must surface a
    mid-frame EOF (never half-filled data reported as success) and the
    sender latches like a dead peer."""

    def test_torn_send_fails_both_ends(self, store):
        fi.configure(
            {"rules": [{"site": "rpc.send", "match": "peer1", "nth": 1,
                        "action": "torn", "frac": 0.5}]}
        )
        colls = [
            CollectivesTcp(
                hostname="localhost", timeout=timedelta(seconds=5)
            )
            for _ in range(2)
        ]
        payload = np.arange(4096, dtype=np.float32)
        sentinel = np.full(4096, -7.0, dtype=np.float32)
        errs = {}

        def run(rank):
            colls[rank].configure(f"{store.address()}/torn", rank, 2)
            try:
                if rank == 0:
                    colls[rank].send(payload, dst=1, tag=5).wait()
                else:
                    buf = sentinel.copy()
                    try:
                        colls[rank].recv(buf, src=0, tag=5).wait()
                    finally:
                        errs["recv_buf"] = buf.copy()
            except Exception as e:  # noqa: BLE001
                errs[rank] = e
            finally:
                colls[rank].shutdown()

        threads = [
            threading.Thread(target=run, args=(r,)) for r in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        # sender: PeerGoneError naming the injected-torn peer
        assert isinstance(errs.get(0), PeerGoneError), errs
        assert "torn send" in str(errs[0])
        # receiver: the stream error surfaces — NEVER a silent success
        # over a half-filled buffer
        assert isinstance(
            errs.get(1), (ConnectionError, TimeoutError, OSError)
        ), errs
        # the torn frame shipped half the payload; whatever landed, the
        # op failed loudly, so staleness can't be mistaken for data
        assert not np.array_equal(errs["recv_buf"], payload)

    def test_torn_cma_pull_fills_prefix_then_raises(self):
        """cma.pull torn semantics against a local buffer (pull from our
        own pid): prefix filled, remainder untouched, loud failure."""
        import ctypes

        src = (ctypes.c_char * 64).from_buffer_copy(bytes(range(64)))
        dst = bytearray(64)
        fi.configure(
            {"rules": [{"site": "cma.pull", "nth": 1, "action": "torn",
                        "frac": 0.25}]}
        )
        from torchft_tpu.collectives import _cma_pull

        with pytest.raises(ConnectionError, match="torn CMA pull"):
            _cma_pull(
                os.getpid(), ctypes.addressof(src), memoryview(dst)
            )
        assert bytes(dst[:16]) == bytes(range(16))
        assert bytes(dst[16:]) == b"\x00" * 48


def _train_group(gid, lighthouse_addr, steps, barrier):
    from torchft_tpu.manager import Manager

    store = StoreServer()
    manager = Manager(
        # python-ring plane: the injected rpc.recv site lives on the
        # Python wire path (the native plane has its own env-gated
        # injection points, exercised by the faultmatrix tier)
        collectives=CollectivesTcp(
            timeout=timedelta(seconds=15), native_plane=False
        ),
        load_state_dict=lambda s: None,
        state_dict=lambda: {"w": np.zeros(4, np.float32)},
        min_replica_size=2,
        replica_id=f"faultinject_g{gid}_",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse_addr,
        timeout=timedelta(seconds=15),
        quorum_timeout=timedelta(seconds=30),
    )
    committed = aborted = 0
    grad = None
    try:
        barrier.wait(timeout=30)
        while committed < steps and aborted < 8:
            manager.start_quorum()
            grad = np.full(8, float(gid + 1), np.float32)
            manager.allreduce(grad).wait()
            if manager.should_commit():
                committed += 1
            else:
                aborted += 1
        return {
            "gid": gid,
            "committed": committed,
            "aborted": aborted,
            "grad": grad,
        }
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def test_2replica_commit_vote_delay_and_recv_error():
    """Fast in-process integration (no multi-process soak cost): one
    ``commit.vote`` delay + one ``rpc.recv`` error injected into a
    2-replica run. The errored step must ABORT (no corrupt average
    commits) and the cohort still reaches the target committed steps."""
    from torchft_tpu.coordination import LighthouseServer

    telemetry.EVENTS.clear()
    fi.configure(
        {
            "seed": 5,
            "rules": [
                {"site": "commit.vote", "match": "rpc", "nth": 2,
                 "action": "delay", "ms": 100},
                {"site": "rpc.recv", "nth": 3, "action": "error",
                 "exc": "ConnectionError", "msg": "injected wire error"},
            ],
        }
    )
    lh = LighthouseServer(bind="[::]:0", min_replicas=2)
    steps = 3
    barrier = threading.Barrier(2)
    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            futs = [
                pool.submit(_train_group, g, lh.address(), steps, barrier)
                for g in range(2)
            ]
            results = [f.result(timeout=120) for f in futs]
    finally:
        lh.shutdown()

    plane = fi.active()
    fired = plane.fired_sequence()
    assert ("commit.vote", "rpc", "delay", 2) in fired, fired
    assert any(
        site == "rpc.recv" and action == "error"
        for site, _m, action, _h in fired
    ), fired

    # both groups committed every target step...
    assert all(r["committed"] == steps for r in results), results
    # ...and the injected wire error aborted its step instead of
    # committing a half-reduced buffer (global conjunction: both sides
    # record the abort)
    assert any(r["aborted"] >= 1 for r in results), results
    kinds = [e["event"] for e in telemetry.EVENTS.recent()]
    assert "abort" in kinds
    assert "fault_injected" in kinds
    # every COMMITTED step averaged cleanly: (1+2)/2 on both groups
    for r in results:
        np.testing.assert_allclose(r["grad"], 1.5)


@pytest.mark.faultmatrix
class TestFaultMatrix:
    """Multi-process scenario matrix (excluded from tier-1; also
    runnable as `python -m torchft_tpu.faultinject.runner`)."""

    @pytest.mark.parametrize(
        "name",
        [
            "torn_cma_pull", "kill_allreduce_cma", "ckpt_serve_death",
            "straggler_group", "perf_regression", "diagnose_straggler",
        ],
    )
    def test_scenario(self, tmp_path, name):
        from torchft_tpu.faultinject import runner

        scn = {s.name: s for s in runner.SCENARIOS}[name]
        if name == "straggler_group":
            # custom two-leg runner: injected skew + control soak, with
            # the fleet straggler detector hosted by this process
            res = runner.run_straggler_scenario(
                scn, str(tmp_path / name), steps=12, timeout_s=420
            )
        elif name == "diagnose_straggler":
            # custom two-leg runner: the victim hosts its own detector +
            # diagnosis engine, and the injected leg must auto-capture
            # exactly one bundle (ISSUE 12)
            res = runner.run_diagnose_scenario(
                scn, str(tmp_path / name), steps=24, timeout_s=420
            )
        elif name == "perf_regression":
            # custom three-leg runner (control + mid-run onset +
            # kill/respawn persistence) with the regression sentinel and
            # critical-path monitors hosted by this process
            res = runner.run_perf_regression_scenario(
                scn, str(tmp_path / name), timeout_s=600
            )
        else:
            res = runner.run_scenario(
                scn, str(tmp_path / name), steps=10, timeout_s=420
            )
        if res.status == "environmental":
            pytest.skip(f"documented environmental corruption: {res.detail}")
        assert res.status == "passed", res
