"""Wire-codec + error-feedback tests (docs/wire_plane.md).

Covers the PR 6 satellite checklist: quantize/dequantize round-trip
bounds, error-feedback residual carry across steps (the sum of applied
updates converges to the sum of true gradients), bit-identity of the
decoded average across ranks on BOTH wire planes, commit-lineage
rollback, and heal/checkpoint round-trip of accumulator state. The
tiny-size smoke tests keep the compression path exercised in tier-1 on
every run.
"""

import struct
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.wire_codec import (
    Bf16Codec,
    ErrorFeedback,
    F32Codec,
    Int8Codec,
    LowRankErrorFeedback,
    get_codec,
    lowrank_basis,
    lowrank_compress,
    lowrank_decompress,
    lowrank_eligible,
)


def _roundtrip(codec, arr):
    out = arr.copy()
    codec.roundtrip(out)
    return out


class TestCodecs:
    def test_registry(self):
        assert isinstance(get_codec(None), F32Codec)
        assert isinstance(get_codec("f32"), F32Codec)
        assert isinstance(get_codec("bfloat16"), Bf16Codec)
        assert isinstance(get_codec("int8"), Int8Codec)
        with pytest.raises(ValueError):
            get_codec("fp4")

    def test_f32_roundtrip_exact(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal(1001).astype(np.float32)
        assert np.array_equal(_roundtrip(F32Codec(), a), a)

    def test_bf16_roundtrip_bound(self):
        rng = np.random.default_rng(1)
        a = rng.standard_normal(4096).astype(np.float32)
        got = _roundtrip(Bf16Codec(), a)
        # bf16 keeps 8 mantissa bits: relative error <= 2^-8
        np.testing.assert_allclose(got, a, rtol=2**-8, atol=1e-30)
        # matches the numpy astype semantics the native plane mirrors
        import ml_dtypes

        np.testing.assert_array_equal(
            got, a.astype(ml_dtypes.bfloat16).astype(np.float32)
        )

    def test_int8_roundtrip_bound(self):
        rng = np.random.default_rng(2)
        a = (rng.standard_normal(4096) * 3.7).astype(np.float32)
        got = _roundtrip(Int8Codec(), a)
        amax = float(np.abs(a).max())
        # half a quantization step, plus fp slack
        assert float(np.abs(got - a).max()) <= amax / 127.0 * 0.5 * 1.01

    def test_int8_wire_format(self):
        codec = Int8Codec()
        a = np.array([0.0, 127.0, -127.0, 63.5], dtype=np.float32)
        w = bytes(codec.encode_into(a))
        assert len(w) == 4 + a.size
        (scale,) = struct.unpack("<f", w[:4])
        assert scale == pytest.approx(1.0)
        q = np.frombuffer(w[4:], dtype=np.int8)
        # 63.5/1.0 rounds half-to-even -> 64
        assert q.tolist() == [0, 127, -127, 64]

    def test_int8_roundtrip_idempotent(self):
        # projecting twice must land on the same grid point: the error-
        # feedback contract (apply() projects, the wire re-encodes)
        rng = np.random.default_rng(3)
        a = rng.standard_normal(512).astype(np.float32)
        codec = Int8Codec()
        once = _roundtrip(codec, a)
        twice = _roundtrip(codec, once)
        np.testing.assert_array_equal(once, twice)

    def test_int8_nan_propagates(self):
        codec = Int8Codec()
        a = np.array([1.0, np.nan, 2.0], dtype=np.float32)
        got = _roundtrip(codec, a)
        assert np.isnan(got).all(), "NaN must poison the chunk loudly"
        a = np.array([1.0, np.inf], dtype=np.float32)
        assert np.isnan(_roundtrip(codec, a)).all()

    def test_int8_zero_chunk(self):
        codec = Int8Codec()
        a = np.zeros(17, dtype=np.float32)
        np.testing.assert_array_equal(_roundtrip(codec, a), a)

    def test_empty_chunk(self):
        for codec in (Bf16Codec(), Int8Codec()):
            a = np.empty(0, dtype=np.float32)
            codec.roundtrip(a)  # must not raise

    def test_wire_nbytes(self):
        assert F32Codec().wire_nbytes(10) == 40
        assert Bf16Codec().wire_nbytes(10) == 20
        assert Int8Codec().wire_nbytes(10) == 14


class TestErrorFeedback:
    def test_rejects_exact_codec(self):
        with pytest.raises(ValueError):
            ErrorFeedback(F32Codec())

    def test_residual_carry_converges(self):
        """EF-SGD invariant: sum(applied_t) = sum(g_t) − e_T, so the
        averaged applied update converges to the true gradient at 1/T
        while naive quantization keeps a constant bias."""
        rng = np.random.default_rng(4)
        g = (rng.standard_normal(256) * 0.01).astype(np.float32)
        ef = ErrorFeedback(Int8Codec())
        naive_codec = Int8Codec()
        applied_sum = np.zeros_like(g)
        naive_sum = np.zeros_like(g)
        steps = 64
        for _ in range(steps):
            buf = g.copy()
            ef.apply("b0_256", buf)
            ef.commit()
            applied_sum += buf
            nb = g.copy()
            naive_codec.roundtrip(nb)
            naive_sum += nb
        amax = float(np.abs(g).max())
        ef_err = float(np.abs(applied_sum / steps - g).max())
        naive_err = float(np.abs(naive_sum / steps - g).max())
        # EF's residual is bounded by ONE step's quantization error
        assert ef_err <= amax / 127.0 / steps * 2.0
        # and it beats the naive bias by an order of magnitude here
        assert ef_err < naive_err / 5.0

    def test_rollback_discards_pending_only(self):
        g = np.linspace(-1, 1, 64, dtype=np.float32)
        ef = ErrorFeedback(Int8Codec())
        buf = g.copy()
        ef.apply("k", buf)
        ef.commit()
        acc_after_commit = ef.state_dict()["acc"]["k"].copy()
        buf2 = g.copy()
        ef.apply("k", buf2)
        assert ef.pending_keys() == ("k",)
        ef.rollback()
        assert ef.pending_keys() == ()
        np.testing.assert_array_equal(
            ef.state_dict()["acc"]["k"], acc_after_commit
        )

    def test_size_change_drops_stale_residual(self):
        ef = ErrorFeedback(Int8Codec())
        buf = np.ones(8, dtype=np.float32)
        ef.apply("k", buf)
        ef.commit()
        big = np.ones(16, dtype=np.float32)
        ef.apply("k", big)  # must not mis-add the 8-elem residual
        ef.commit()
        assert ef.state_dict()["acc"]["k"].size == 16

    def test_state_dict_roundtrip(self):
        ef = ErrorFeedback(Int8Codec())
        buf = np.linspace(0, 1, 32, dtype=np.float32)
        ef.apply("k", buf)
        ef.commit()
        state = ef.state_dict()
        assert state["codec"] == "int8"
        ef2 = ErrorFeedback(Int8Codec())
        ef2.load_state_dict(state)
        np.testing.assert_array_equal(
            ef2.state_dict()["acc"]["k"], state["acc"]["k"]
        )

    def test_codec_mismatch_drops_accumulators(self):
        ef = ErrorFeedback(Int8Codec())
        buf = np.ones(4, dtype=np.float32)
        ef.apply("k", buf)
        ef.commit()
        ef2 = ErrorFeedback(Bf16Codec())
        ef2.load_state_dict(ef.state_dict())
        assert ef2.state_dict()["acc"] == {}

    def test_pending_excluded_from_state_dict(self):
        ef = ErrorFeedback(Int8Codec())
        buf = np.ones(4, dtype=np.float32)
        ef.apply("k", buf)  # staged, not committed
        assert ef.state_dict()["acc"] == {}


# ---------------------------------------------------------------------------
# optimizer integration (stub manager; the live 2-group path is covered
# by the faultmatrix kill_streamed_bucket / torn_compressed_frame runs)
# ---------------------------------------------------------------------------


class _WireStubManager:
    """Single-group manager stand-in reporting a lossy wire codec."""

    def __init__(self, commits, codec="int8"):
        self._commits = list(commits)
        self._codec = codec
        self._load = None
        self._save = None

    def wire_codec(self):
        return self._codec

    def set_state_dict_fns(self, load, save):
        self._load, self._save = load, save

    def pending_commit(self):
        return None

    def start_quorum(self, **kw):
        pass

    def speculation_allowed(self):
        return False

    def device_data_plane(self):
        return False

    def is_participating(self):
        return True

    def num_participants(self):
        return 1

    def errored(self):
        return None

    def allreduce_many(self, arrays):
        from torchft_tpu.futures import Future

        return Future.completed(arrays)

    def should_commit(self):
        return self._commits.pop(0)


class TestManagedOptimizerEF:
    def _opt(self, commits, codec="int8"):
        import optax

        from torchft_tpu.optim import ManagedOptimizer

        mgr = _WireStubManager(commits, codec=codec)
        opt = ManagedOptimizer(mgr, optax.sgd(1.0))
        opt.init({"w": np.zeros(64, dtype=np.float32)})
        return opt

    def test_auto_enabled_for_lossy_codec(self):
        assert self._opt([True]).error_feedback is not None
        assert self._opt([True], codec="f32").error_feedback is None

    def test_env_veto(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_WIRE_EF", "0")
        assert self._opt([True]).error_feedback is None

    def test_commit_promotes_abort_rolls_back(self):
        opt = self._opt([True, False, True])
        g = {"w": np.full(64, 0.013, dtype=np.float32)}
        opt.step({k: v.copy() for k, v in g.items()})  # committed
        ef = opt.error_feedback
        acc1 = ef.state_dict()["acc"]
        assert acc1, "committed step must promote its residual"
        w1 = opt.params["w"].copy()
        opt.step({k: v.copy() for k, v in g.items()})  # aborted
        np.testing.assert_array_equal(np.asarray(opt.params["w"]), w1)
        for k, v in ef.state_dict()["acc"].items():
            np.testing.assert_array_equal(v, acc1[k])
        opt.step({k: v.copy() for k, v in g.items()})  # committed again
        assert not np.array_equal(np.asarray(opt.params["w"]), w1)

    def test_heal_roundtrip_carries_accumulators(self):
        opt = self._opt([True])
        g = {"w": np.full(64, 0.007, dtype=np.float32)}
        opt.step({k: v.copy() for k, v in g.items()})
        state = opt.state_dict()
        assert "ef" in state and state["ef"]["acc"]
        opt2 = self._opt([True])
        opt2.load_state_dict(state)
        got = opt2.error_feedback.state_dict()["acc"]
        want = state["ef"]["acc"]
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])

    def test_heal_adopts_ef_state_before_lazy_creation(self):
        # a proxied backend reports "f32" until its first configure: the
        # EF instance doesn't exist yet when a heal lands — the healed
        # accumulators must be ADOPTED (created from the state's own
        # codec), not silently dropped
        donor = self._opt([True])
        g = {"w": np.full(64, 0.007, dtype=np.float32)}
        donor.step({k: v.copy() for k, v in g.items()})
        state = donor.state_dict()
        healer = self._opt([True], codec="f32")  # plane not lossy YET
        assert healer.error_feedback is None
        healer.load_state_dict(state)
        assert healer.error_feedback is not None
        got = healer.error_feedback.state_dict()["acc"]
        for k, v in state["ef"]["acc"].items():
            np.testing.assert_array_equal(got[k], v)

    def test_heal_without_ef_state_starts_clean(self):
        opt = self._opt([True, True])
        g = {"w": np.full(64, 0.007, dtype=np.float32)}
        opt.step({k: v.copy() for k, v in g.items()})
        opt.load_state_dict(
            {"params": opt.params, "opt_state": opt.opt_state}
        )
        assert opt.error_feedback.state_dict()["acc"] == {}


# ---------------------------------------------------------------------------
# tiny-size tier-1 smoke: the compressed wire exercised on every run,
# bit-identity asserted on both planes
# ---------------------------------------------------------------------------


def _ring_world(store, world, codec, prefix, **kw):
    from torchft_tpu.collectives import CollectivesTcp, ReduceOp

    colls = [
        CollectivesTcp(
            hostname="localhost",
            timeout=timedelta(seconds=15),
            wire_dtype=codec,
            **kw,
        )
        for _ in range(world)
    ]

    def start(rank):
        colls[rank].configure(f"{store.address()}/{prefix}", rank, world)
        rng = np.random.default_rng(100 + rank)
        a = rng.standard_normal(10007).astype(np.float32)
        ref = a.copy()
        out = colls[rank].allreduce([a], ReduceOp.AVG).wait(
            timedelta(seconds=20)
        )
        info = (colls[rank].plane_info(), colls[rank].wire_codec())
        colls[rank].shutdown()
        return ref, out[0], info

    with ThreadPoolExecutor(max_workers=world) as ex:
        return list(ex.map(start, range(world)))


@pytest.fixture()
def store():
    from torchft_tpu.store import StoreServer

    s = StoreServer()
    yield s
    s.shutdown()


class TestCompressedWireSmoke:
    @pytest.mark.parametrize("codec", ["int8", "bfloat16"])
    def test_python_ring_bit_identical(self, store, monkeypatch, codec):
        monkeypatch.setenv("TORCHFT_NATIVE_PLANE", "0")
        outs = _ring_world(store, 3, codec, f"pyring{codec}")
        assert outs[0][2] == ("python-ring", codec)
        for _, got, _info in outs[1:]:
            np.testing.assert_array_equal(got, outs[0][1])
        expect = np.mean([r for r, _, _ in outs], axis=0)
        rtol = 0.02 if codec == "int8" else 0.01
        np.testing.assert_allclose(
            outs[0][1], expect, rtol=rtol, atol=rtol
        )

    @pytest.mark.parametrize("codec", ["int8", "bfloat16"])
    def test_native_striped_bit_identical(self, store, monkeypatch, codec):
        monkeypatch.setenv("TORCHFT_DP_CMA", "0")
        outs = _ring_world(store, 3, codec, f"native{codec}")
        assert outs[0][2] == ("tcp-striped", codec)
        for _, got, _info in outs[1:]:
            np.testing.assert_array_equal(got, outs[0][1])
        expect = np.mean([r for r, _, _ in outs], axis=0)
        rtol = 0.02 if codec == "int8" else 0.01
        np.testing.assert_allclose(
            outs[0][1], expect, rtol=rtol, atol=rtol
        )

    def test_cma_bypasses_codec(self, store):
        # same-host CMA moves exact f32: wire_codec() must say so, which
        # is also what disables error-feedback compensation per step
        outs = _ring_world(store, 2, "int8", "cmacodec")
        assert outs[0][2] == ("cma", "f32")
        expect = (outs[0][0] + outs[1][0]) / 2.0
        np.testing.assert_allclose(outs[0][1], expect, rtol=1e-6)

    def test_env_codec_default(self, store, monkeypatch):
        monkeypatch.setenv("TORCHFT_WIRE_CODEC", "int8")
        monkeypatch.setenv("TORCHFT_NATIVE_PLANE", "0")
        outs = _ring_world(store, 2, None, "envcodec")
        assert outs[0][2] == ("python-ring", "int8")


# ---------------------------------------------------------------------------
# DiLoCo outer-step low-rank projection
# ---------------------------------------------------------------------------


class TestLowRank:
    def test_basis_deterministic(self):
        q1 = lowrank_basis((64, 32), 4, seed=7)
        q2 = lowrank_basis((64, 32), 4, seed=7)
        np.testing.assert_array_equal(q1, q2)
        assert q1.shape == (32, 4)
        # orthonormal columns
        np.testing.assert_allclose(
            q1.T @ q1, np.eye(4, dtype=np.float32), atol=1e-5
        )
        assert not np.array_equal(q1, lowrank_basis((64, 32), 4, seed=8))

    def test_eligibility(self):
        assert lowrank_eligible((64, 32), 4)
        assert not lowrank_eligible((64,), 4)
        assert not lowrank_eligible((64, 8), 4)  # min dim < 4r
        assert not lowrank_eligible((64, 32), 0)

    def test_projection_error_feedback_converges(self):
        """Residual carry across outer syncs: the averaged applied
        pseudogradient approaches the true one at 1/T even though each
        sync ships only a rank-4 projection."""
        rng = np.random.default_rng(9)
        m = rng.standard_normal((48, 32)).astype(np.float32)
        ef = LowRankErrorFeedback()
        applied_sum = np.zeros_like(m)
        one_shot = lowrank_decompress(
            lowrank_compress(m, lowrank_basis(m.shape, 4, seed=0)),
            lowrank_basis(m.shape, 4, seed=0),
        )
        steps = 48
        for t in range(steps):
            comp = ef.compensate("l0", m)
            q = lowrank_basis(m.shape, 4, seed=t)
            p = lowrank_compress(comp, q)
            approx = lowrank_decompress(p, q)
            ef.stage("l0", comp, approx)
            ef.commit()
            applied_sum += approx
        ef_err = float(np.abs(applied_sum / steps - m).max())
        shot_err = float(np.abs(one_shot - m).max())
        assert ef_err < shot_err / 3.0

    def test_rollback_contract(self):
        m = np.ones((16, 16), dtype=np.float32)
        ef = LowRankErrorFeedback()
        q = lowrank_basis(m.shape, 2, seed=0)
        comp = ef.compensate("l0", m)
        ef.stage("l0", comp, lowrank_decompress(lowrank_compress(comp, q), q))
        ef.rollback()
        np.testing.assert_array_equal(ef.compensate("l0", m), m)

    def test_diloco_state_dict_carries_lr_ef(self):
        import optax

        from torchft_tpu.local_sgd import DiLoCo

        class _Mgr(_WireStubManager):
            _use_async_quorum = False

            def commit_pipeline_enabled(self):
                return False

        mgr = _Mgr([True, True], codec="f32")
        diloco = DiLoCo(mgr, optax.sgd(1.0), sync_every=1, outer_rank=2)
        params = {"w": np.zeros((32, 16), dtype=np.float32)}
        diloco.save(params)
        stepped = {
            "w": np.full((32, 16), 0.25, dtype=np.float32)
        }
        out = diloco.step(stepped)
        state = diloco.state_dict()
        assert state["outer_syncs"] == 1
        assert "lr_ef" in state and state["lr_ef"]["acc"]
        # the outer step descended toward the inner progress
        assert float(np.asarray(out["w"]).mean()) > 0.0
