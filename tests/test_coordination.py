"""Coordination-core tests.

Ports the reference's in-file Rust test scenarios to the C++ core:
  * quorum_compute table tests  (src/lighthouse.rs:582-1001)
  * compute_quorum_results tables (src/manager.rs:720-850)
  * live lighthouse/manager e2e    (src/lighthouse.rs:910-952,
    src/manager.rs:504-549)
"""

import threading
import time
import urllib.request
from datetime import timedelta

import pytest

from torchft_tpu import _native
from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    ManagerClient,
    ManagerServer,
)


def member(rid, step=0, shrink_only=False, world_size=1):
    return {
        "replica_id": rid,
        "address": f"addr_{rid}",
        "store_address": f"store_{rid}",
        "step": step,
        "world_size": world_size,
        "shrink_only": shrink_only,
    }


def state(now, participants, heartbeats, prev=None, **opt):
    return {
        "now": now,
        "participants": [
            {"joined_ms": j, "member": m} for j, m in participants
        ],
        "heartbeats": [{"replica_id": r, "at_ms": t} for r, t in heartbeats],
        "prev_quorum": prev,
        "opt": {
            "min_replicas": opt.get("min_replicas", 1),
            "join_timeout_ms": opt.get("join_timeout_ms", 60000),
            "heartbeat_timeout_ms": opt.get("heartbeat_timeout_ms", 5000),
        },
    }


def quorum(qid, members):
    return {"quorum_id": qid, "participants": members, "created": 0}


class TestQuorumCompute:
    def test_empty(self):
        r = _native.quorum_compute(state(1000, [], []))
        assert r["quorum"] is None

    def test_join_timeout_waits_for_stragglers(self):
        # two participants + one extra heartbeating replica (2 of 3 passes
        # the split-brain guard), within join_timeout -> wait
        # (src/lighthouse.rs test_quorum_join_timeout)
        s = state(
            1000,
            [(1000, member("a")), (1000, member("b"))],
            [("a", 1000), ("b", 1000), ("c", 1000)],
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None
        assert "straggler" in r["reason"]

        # after the join timeout has elapsed the quorum forms without c
        s = state(
            70000,
            [(1000, member("a")), (1000, member("b"))],
            [("a", 69999), ("b", 69999), ("c", 69999)],
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is not None
        assert [m["replica_id"] for m in r["quorum"]] == ["a", "b"]

    def test_split_brain_beats_straggler_wait(self):
        # 1 participant of 2 heartbeating is rejected by the split-brain
        # guard before the straggler wait is even considered
        s = state(
            1000,
            [(1000, member("a"))],
            [("a", 1000), ("b", 1000)],
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None
        assert "at least half" in r["reason"]

    def test_all_joined_skips_join_timeout(self):
        s = state(
            1000,
            [(1000, member("a")), (1000, member("b"))],
            [("a", 1000), ("b", 1000)],
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is not None
        assert len(r["quorum"]) == 2

    def test_heartbeat_expiry_excludes_replica(self):
        # a's heartbeat is stale -> unhealthy -> below min_replicas
        s = state(
            10000,
            [(1000, member("a"))],
            [("a", 1000)],
            heartbeat_timeout_ms=5000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None
        assert "min_replicas" in r["reason"]

    def test_min_replicas(self):
        s = state(
            1000,
            [(1000, member("a"))],
            [("a", 1000)],
            min_replicas=2,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None

    def test_fast_quorum_when_prev_members_all_healthy(self):
        # prev quorum {a, b}; both are healthy participants again; extra
        # heartbeating straggler c does NOT delay the fast path
        # (src/lighthouse.rs:174-187)
        s = state(
            1000,
            [(999, member("a")), (999, member("b"))],
            [("a", 1000), ("b", 1000), ("c", 1000)],
            prev=quorum(1, [member("a"), member("b")]),
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is not None
        assert "Fast quorum" in r["reason"]
        assert [m["replica_id"] for m in r["quorum"]] == ["a", "b"]

    def test_no_fast_quorum_when_prev_member_missing(self):
        s = state(
            1000,
            [(999, member("a"))],
            [("a", 1000), ("b", 1000)],
            prev=quorum(1, [member("a"), member("b")]),
            join_timeout_ms=60000,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None  # waiting for straggler b

    def test_split_brain_guard(self):
        # 2 participants out of 5 heartbeating: 2 <= 5//2 -> rejected
        # (src/lighthouse.rs:202-213)
        s = state(
            100000,
            [(1, member("a")), (1, member("b"))],
            [(r, 100000) for r in ["a", "b", "c", "d", "e"]],
            join_timeout_ms=1,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is None
        assert "at least half" in r["reason"]

        # 3 of 5 passes
        s = state(
            100000,
            [(1, member("a")), (1, member("b")), (1, member("c"))],
            [(r, 100000) for r in ["a", "b", "c", "d", "e"]],
            join_timeout_ms=1,
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is not None

    def test_shrink_only_filters_joiners(self):
        # shrink_only quorum keeps only prev members; c is excluded even
        # though healthy (src/lighthouse.rs:167-172 + 1036-1140 scenario)
        s = state(
            1000,
            [
                (999, member("a", shrink_only=True)),
                (999, member("b")),
                (999, member("c")),
            ],
            [("a", 1000), ("b", 1000), ("c", 1000)],
            prev=quorum(1, [member("a"), member("b")]),
        )
        r = _native.quorum_compute(s)
        assert r["quorum"] is not None
        assert [m["replica_id"] for m in r["quorum"]] == ["a", "b"]

    def test_candidates_sorted_by_replica_id(self):
        s = state(
            1000,
            [(1000, member("z")), (1000, member("a")), (1000, member("m"))],
            [("z", 1000), ("a", 1000), ("m", 1000)],
        )
        r = _native.quorum_compute(s)
        assert [m["replica_id"] for m in r["quorum"]] == ["a", "m", "z"]


class TestComputeQuorumResults:
    def test_first_step_primary_and_recovery(self):
        # max_step == 0: non-primary replicas bootstrap from the primary
        # (src/manager.rs:403-416 + test_compute_quorum_results_first_step)
        q = quorum(1, [member("a", 0), member("b", 0)])
        ra = _native.compute_quorum_results(q, "a", 0)
        rb = _native.compute_quorum_results(q, "b", 0)
        assert ra["heal"] is False
        assert ra["recover_dst_ranks"] == [1]
        assert ra["store_address"] == "store_a"
        assert rb["heal"] is True
        assert rb["recover_src_rank"] == 0
        assert rb["recover_src_manager_address"] == "addr_a"
        assert rb["max_world_size"] == 2
        assert rb["replica_world_size"] == 2

    def test_mixed_step_recovery_assignment(self):
        q = quorum(7, [member("a", 5), member("b", 3), member("c", 5)])
        ra = _native.compute_quorum_results(q, "a", 0)
        rb = _native.compute_quorum_results(q, "b", 0)
        rc = _native.compute_quorum_results(q, "c", 0)
        assert ra["max_step"] == 5
        assert ra["max_world_size"] == 2  # cohort {a, c}
        assert ra["recover_dst_ranks"] == [1]
        assert rb["heal"] is True
        assert rb["recover_src_rank"] == 0
        assert rb["max_rank"] is None  # b not in the max cohort
        assert rc["recover_dst_ranks"] == []
        assert rc["max_rank"] == 1

    def test_rank_offsets_recovery_source(self):
        # local rank shifts the round-robin so different local ranks pull
        # from different sources (src/manager.rs:434-447)
        q = quorum(7, [member("a", 5), member("b", 3), member("c", 5)])
        rb0 = _native.compute_quorum_results(q, "b", 0)
        rb1 = _native.compute_quorum_results(q, "b", 1)
        assert rb0["recover_src_rank"] == 0
        assert rb1["recover_src_rank"] == 2

    def test_primary_store_striped_by_rank(self):
        q = quorum(7, [member("a", 5), member("c", 5)])
        r0 = _native.compute_quorum_results(q, "a", 0)
        r1 = _native.compute_quorum_results(q, "a", 1)
        assert r0["store_address"] == "store_a"
        assert r1["store_address"] == "store_c"

    def test_replica_not_in_quorum(self):
        q = quorum(1, [member("a", 0)])
        with pytest.raises(RuntimeError):
            _native.compute_quorum_results(q, "zz", 0)

    def test_group_heal_is_plane_consistent(self):
        """Participation gating must agree across a group's rank planes —
        otherwise plane 0 would average real gradients while plane 1
        averages zeros and replicated/sharded state diverges (extension
        beyond the reference's per-rank gate, manager.py:268). At the
        step-0 bootstrap every group heals from ONE source (the cohort's
        first) rather than the rank-striped primary: striping would make
        every group heal somewhere, zeroing every contribution and turning
        the first committed step into a pure weight-decay update (round-2
        advisor finding)."""
        q = quorum(
            1, [member("a", 0, world_size=2), member("b", 0, world_size=2)]
        )
        # bootstrap source group: no plane heals, contributes real grads
        for rank in (0, 1):
            ra = _native.compute_quorum_results(q, "a", rank)
            assert ra["group_heal"] is False, rank
            assert ra["heal"] is False, rank
            assert ra["recover_dst_ranks"] == [1], rank
        # every other group heals on EVERY plane, from the same source
        for rank in (0, 1):
            rb = _native.compute_quorum_results(q, "b", rank)
            assert rb["group_heal"] is True, rank
            assert rb["heal"] is True, rank
            assert rb["recover_src_rank"] == 0, rank
        # store striping is untouched by the bootstrap rule
        assert _native.compute_quorum_results(q, "a", 0)["store_address"] == "store_a"
        assert _native.compute_quorum_results(q, "a", 1)["store_address"] == "store_b"

    def test_participant_ids_in_rank_order(self):
        q = quorum(7, [member("z", 5), member("a", 5), member("m", 3)])
        r = _native.compute_quorum_results(q, "a", 0)
        ids = [s if isinstance(s, str) else s.decode() for s in r["participant_ids"]]
        assert ids == ["a", "m", "z"]

    def test_group_heal_matches_heal_for_single_rank_groups(self):
        q0 = quorum(1, [member("a", 0), member("b", 0)])
        qk = quorum(7, [member("a", 5), member("b", 3)])
        for q in (q0, qk):
            for rid in ("a", "b"):
                r = _native.compute_quorum_results(q, rid, 0)
                assert r["group_heal"] == r["heal"], (rid, r)


class TestLighthouseE2E:
    def test_quorum_fast_latency(self):
        # parity with lighthouse_test.py:44-47 — single-replica quorum with
        # join_timeout_ms=100 resolves quickly
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            t0 = time.monotonic()
            q = c.quorum(member("a"), timeout=timedelta(seconds=5))
            dt = time.monotonic() - t0
            assert [m["replica_id"] for m in q["participants"]] == ["a"]
            assert q["quorum_id"] == 1
            assert dt < 1.0
            c.close()
        finally:
            lh.shutdown()

    def test_heartbeat(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            c.heartbeat("a")
            c.close()
        finally:
            lh.shutdown()

    def test_dashboard_status(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            c.quorum(member("dash_replica"), timeout=timedelta(seconds=5))
            addr = lh.address()
            with urllib.request.urlopen(addr + "/status", timeout=5) as resp:
                body = resp.read().decode()
            assert "dash_replica" in body
            assert "quorum_id" in body
            with urllib.request.urlopen(addr + "/", timeout=5) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(addr + "/status.json", timeout=5) as resp:
                assert b"quorum_id" in resp.read()
            # Prometheus exposition (beyond the reference: SURVEY §5.5
            # notes it has no metrics export)
            with urllib.request.urlopen(addr + "/metrics", timeout=5) as resp:
                metrics = resp.read().decode()
            assert "torchft_quorum_id" in metrics
            assert "torchft_participants 1" in metrics
            assert 'torchft_member_step{replica_id="dash_replica"} 0' in metrics
            # round-5 FT runtime state (review #9): eviction/flush counters,
            # per-member plane + recovering flags
            assert "torchft_evictions_total 0" in metrics
            assert "torchft_flush_requests_total" in metrics
            assert "torchft_recovering_members 0" in metrics
            assert 'torchft_member_info{replica_id="dash_replica"' in metrics
            c.close()
        finally:
            lh.shutdown()

    def test_status_json_ft_runtime_fields(self):
        """Round-5 review #9: /status.json exposes the FT runtime state —
        per-member plane + recovering flag, eviction and flush counters —
        and an eviction shows up in both counters and the recent list."""
        import json as _json

        lh = LighthouseServer(
            bind="[::]:0", min_replicas=1, join_timeout_ms=100
        )
        try:
            c = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            m = member("json_replica")
            m["plane"] = "cma"
            c.quorum(m, timeout=timedelta(seconds=5))
            with urllib.request.urlopen(
                lh.address() + "/status.json", timeout=5
            ) as resp:
                st = _json.loads(resp.read())
            assert st["evictions_total"] == 0
            assert st["flush_requests_total"] == 0
            assert st["max_step"] == 0
            assert st["members"] == [
                {
                    "replica_id": "json_replica",
                    "step": 0,
                    "plane": "cma",
                    "recovering": False,
                    "commit_failures": 0,
                }
            ]
            assert st["recent_evictions"] == []

            # an eviction (reporter must differ from victim; probe of the
            # fake address fails -> victim evicted) lands in the counters.
            # both members must (re-)request CONCURRENTLY: the split-brain
            # guard refuses to drop a still-heartbeating member, so a
            # sequential second join would wait out the lease instead
            two = member("second_replica")
            two["plane"] = "tcp-striped"
            import threading

            c2 = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            # newcomer FIRST (parks: fast-quorum needs the prev member),
            # then the incumbent re-request completes the pair — if the
            # incumbent went first, its fast-quorum would re-publish the
            # solo quorum before the newcomer registers
            t = threading.Thread(
                target=lambda: c2.quorum(two, timeout=timedelta(seconds=10))
            )
            t.start()
            time.sleep(0.3)
            c.quorum(m, timeout=timedelta(seconds=10))
            t.join()
            evicted = c2.evict(
                reporter="second_replica",
                victim="json_replica",
                timeout=timedelta(seconds=5),
            )
            assert evicted
            with urllib.request.urlopen(
                lh.address() + "/status.json", timeout=5
            ) as resp:
                st = _json.loads(resp.read())
            assert st["evictions_total"] == 1
            assert len(st["recent_evictions"]) == 1
            assert "json_replica < second_replica" in st["recent_evictions"][0]
            c.close()
            c2.close()
        finally:
            lh.shutdown()

    def test_quorum_id_bumps_only_on_membership_change(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            q1 = c.quorum(member("a", step=1), timeout=timedelta(seconds=5))
            q2 = c.quorum(member("a", step=2), timeout=timedelta(seconds=5))
            assert q1["quorum_id"] == q2["quorum_id"]  # same member set
            c.close()
        finally:
            lh.shutdown()

    def test_commit_failures_flush_bumps_quorum_id(self):
        # data-plane flush: a member with latched commit failures forces a
        # quorum_id bump even though membership is unchanged, so every group
        # re-rendezvouses its collectives into a fresh epoch
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            q1 = c.quorum(member("a", step=1), timeout=timedelta(seconds=5))
            flushing = dict(member("a", step=1), commit_failures=1)
            q2 = c.quorum(flushing, timeout=timedelta(seconds=5))
            assert q2["quorum_id"] == q1["quorum_id"] + 1
            # flush consumed: a clean re-request keeps the new id
            q3 = c.quorum(member("a", step=2), timeout=timedelta(seconds=5))
            assert q3["quorum_id"] == q2["quorum_id"]
            c.close()
        finally:
            lh.shutdown()


class TestManagerE2E:
    def _setup(self, n_replicas=2, world_size=1, min_replicas=2):
        lh = LighthouseServer(
            bind="[::]:0", min_replicas=min_replicas, join_timeout_ms=100
        )
        mgrs = [
            ManagerServer(
                replica_id=f"rep_{i}",
                lighthouse_addr=lh.address(),
                hostname="localhost",
                bind="[::]:0",
                store_addr=f"store_{i}",
                world_size=world_size,
            )
            for i in range(n_replicas)
        ]
        return lh, mgrs

    def test_quorum_and_commit(self):
        lh, mgrs = self._setup()
        try:
            results = {}

            def run(i):
                c = ManagerClient(mgrs[i].address(), connect_timeout=timedelta(seconds=10))
                results[i] = c._quorum(
                    rank=0, step=0, checkpoint_metadata=f"m{i}",
                    shrink_only=False, timeout=timedelta(seconds=10),
                )
                results[(i, "commit")] = c.should_commit(
                    0, 0, True, timeout=timedelta(seconds=10)
                )
                c.close()

            ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

            assert results[0].quorum_id == results[1].quorum_id
            assert results[0].replica_world_size == 2
            assert results[(0, "commit")] is True
            assert results[(1, "commit")] is True
            # exactly one of the two bootstraps from the other at step 0
            assert results[0].heal != results[1].heal
        finally:
            for m in mgrs:
                m.shutdown()
            lh.shutdown()

    def test_should_commit_one_failure_rejects_all(self):
        # world_size=2 ranks on one manager; one False vote fails the round
        # (src/manager.rs:295-347 semantics)
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        mgr = ManagerServer(
            replica_id="rep_0", lighthouse_addr=lh.address(),
            hostname="localhost", bind="[::]:0", store_addr="s",
            world_size=2,
        )
        try:
            out = {}

            def vote(rank, val):
                c = ManagerClient(mgr.address(), connect_timeout=timedelta(seconds=10))
                out[rank] = c.should_commit(rank, 0, val, timeout=timedelta(seconds=10))
                c.close()

            ts = [
                threading.Thread(target=vote, args=(0, True)),
                threading.Thread(target=vote, args=(1, False)),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert out[0] is False
            assert out[1] is False

            # next round is reset and can succeed
            ts = [
                threading.Thread(target=vote, args=(0, True)),
                threading.Thread(target=vote, args=(1, True)),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert out[0] is True and out[1] is True
        finally:
            mgr.shutdown()
            lh.shutdown()

    def test_checkpoint_metadata_lookup(self):
        lh, mgrs = self._setup(n_replicas=1, min_replicas=1)
        try:
            c = ManagerClient(mgrs[0].address(), connect_timeout=timedelta(seconds=10))
            c._quorum(
                rank=0, step=0, checkpoint_metadata="the-meta",
                shrink_only=False, timeout=timedelta(seconds=10),
            )
            assert c._checkpoint_metadata(0, timeout=timedelta(seconds=5)) == "the-meta"
            with pytest.raises(RuntimeError):
                c._checkpoint_metadata(99, timeout=timedelta(seconds=5))
            c.close()
        finally:
            mgrs[0].shutdown()
            lh.shutdown()

    def test_quorum_timeout_enforced(self):
        # 1 of 2 local ranks joins -> quorum can't proceed; 10ms deadline
        # must raise TimeoutError in well under a second
        # (manager_integ_test.py:356-368 parity)
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        mgr = ManagerServer(
            replica_id="rep_0", lighthouse_addr=lh.address(),
            hostname="localhost", bind="[::]:0", store_addr="s",
            world_size=2,
        )
        try:
            c = ManagerClient(mgr.address(), connect_timeout=timedelta(seconds=10))
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                c._quorum(
                    rank=0, step=0, checkpoint_metadata="",
                    shrink_only=False, timeout=timedelta(milliseconds=10),
                )
            assert time.monotonic() - t0 < 1.0
            c.close()
        finally:
            mgr.shutdown()
            lh.shutdown()

    def test_soft_kill(self):
        lh, mgrs = self._setup(n_replicas=1, min_replicas=1)
        try:
            c = ManagerClient(mgrs[0].address(), connect_timeout=timedelta(seconds=10))
            c.kill("test")  # TORCHFT_TPU_SOFT_KILL set by conftest
            c.close()
        finally:
            mgrs[0].shutdown()
            lh.shutdown()

    def test_manager_requires_lighthouse(self):
        with pytest.raises((RuntimeError, TimeoutError)):
            ManagerServer(
                replica_id="rep_0",
                lighthouse_addr="http://localhost:1",  # nothing listening
                hostname="localhost", bind="[::]:0", store_addr="s",
                world_size=1,
                connect_timeout=timedelta(milliseconds=200),
            )


class TestEviction:
    """Survivor-reported eviction (lh.evict): active dead-peer detection
    that beats the passive heartbeat-lease floor the reference shares
    (src/lighthouse.rs:119-128 only ages out leases)."""

    def _quorum_pair(self, lh, mgrs):
        """Drive both managers through one quorum so prev_quorum exists."""
        results = {}

        def run(i):
            c = ManagerClient(mgrs[i].address(), connect_timeout=timedelta(seconds=10))
            results[i] = c._quorum(
                rank=0, step=1, checkpoint_metadata="",
                shrink_only=False, timeout=timedelta(seconds=10),
            )
            c.close()

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(mgrs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return results

    def test_false_report_does_not_evict_live_peer(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=2, join_timeout_ms=100)
        mgrs = [
            ManagerServer(
                replica_id=f"rep_{i}", lighthouse_addr=lh.address(),
                hostname="localhost", bind="[::]:0", store_addr=f"s{i}",
                world_size=1,
            )
            for i in range(2)
        ]
        try:
            self._quorum_pair(lh, mgrs)
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            # rep_1 is alive and listening — the probe succeeds, report is
            # a no-op
            assert c.evict("rep_0", "rep_1") is False
            # the next quorum still contains both members
            res = self._quorum_pair(lh, mgrs)
            assert res[0].replica_world_size == 2
            assert sorted(res[0].participant_ids) == ["rep_0", "rep_1"]
            c.close()
        finally:
            for m in mgrs:
                m.shutdown()
            lh.shutdown()

    def test_dead_peer_evicted_without_lease_wait(self):
        # long heartbeat lease: only eviction (not expiry) can explain a
        # fast quorum without the victim
        lh = LighthouseServer(
            bind="[::]:0", min_replicas=1, join_timeout_ms=60000,
            heartbeat_timeout_ms=60000,
        )
        mgrs = [
            ManagerServer(
                replica_id=f"rep_{i}", lighthouse_addr=lh.address(),
                hostname="localhost", bind="[::]:0", store_addr=f"s{i}",
                world_size=1,
            )
            for i in range(2)
        ]
        try:
            self._quorum_pair(lh, mgrs)
            mgrs[1].shutdown()  # SIGKILL stand-in: socket gone, no goodbyes
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            t0 = time.monotonic()
            assert c.evict("rep_0", "rep_1") is True
            # survivor re-quorums immediately — no 60s lease, no join wait
            mc = ManagerClient(mgrs[0].address(), connect_timeout=timedelta(seconds=10))
            r = mc._quorum(
                rank=0, step=2, checkpoint_metadata="",
                shrink_only=False, timeout=timedelta(seconds=10),
            )
            assert time.monotonic() - t0 < 2.0
            assert r.replica_world_size == 1
            assert r.participant_ids == ["rep_0"]
            mc.close()
            c.close()
        finally:
            for m in mgrs:
                m.shutdown()
            lh.shutdown()

    def test_evict_guards(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        mgr = ManagerServer(
            replica_id="rep_0", lighthouse_addr=lh.address(),
            hostname="localhost", bind="[::]:0", store_addr="s0",
            world_size=1,
        )
        try:
            c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
            # no quorum yet
            with pytest.raises(RuntimeError):
                c.evict("rep_0", "rep_1")
            self._quorum_pair(lh, [mgr])
            # reporter not a member
            with pytest.raises(RuntimeError):
                c.evict("stranger", "rep_0")
            # victim not a member
            with pytest.raises(RuntimeError):
                c.evict("rep_0", "stranger")
            # self-report
            with pytest.raises(RuntimeError):
                c.evict("rep_0", "rep_0")
            c.close()
        finally:
            mgr.shutdown()
            lh.shutdown()

    def test_manager_forwards_evict(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=2, join_timeout_ms=100)
        mgrs = [
            ManagerServer(
                replica_id=f"rep_{i}", lighthouse_addr=lh.address(),
                hostname="localhost", bind="[::]:0", store_addr=f"s{i}",
                world_size=1,
            )
            for i in range(2)
        ]
        try:
            self._quorum_pair(lh, mgrs)
            mgrs[1].shutdown()
            mc = ManagerClient(mgrs[0].address(), connect_timeout=timedelta(seconds=10))
            assert mc.evict("rep_1") is True
            mc.close()
        finally:
            for m in mgrs:
                m.shutdown()
            lh.shutdown()
