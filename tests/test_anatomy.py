"""Step-anatomy plane tests (ISSUE 8): ledger phase accounting, the
wire-stage shim, native latency histograms (+ exact cross-process merge),
the lighthouse piggyback round-trip, burn-rate SLO math and straggler
latch/unlatch hysteresis."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from datetime import timedelta

import pytest

from torchft_tpu import telemetry
from torchft_tpu.telemetry.anatomy import (
    BARRIER_PHASES,
    LOG2_BUCKETS,
    PHASES,
    StepLedger,
    lathist_quantile,
    merge_lathist,
)
from torchft_tpu.telemetry.slo import BurnRateSlo, StragglerDetector


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# ledger accounting
# ---------------------------------------------------------------------------


class TestStepLedger:
    def test_phases_sum_to_measured_wall_clock(self):
        led = StepLedger()
        led.tick(0)
        t0 = time.perf_counter()
        led.record("compute", 0.02)
        led.record("quorum_wait", 0.01)
        led.record("commit_barrier", 0.005)
        time.sleep(0.06)
        row = led.tick(1)
        wall_measured = time.perf_counter() - t0
        assert row is not None
        # the row's phases sum to the ledger's wall EXACTLY (idle is the
        # residual) ...
        assert sum(row["phases"].values()) == pytest.approx(
            row["wall_s"], rel=1e-9
        )
        # ... and the ledger's wall agrees with an external stopwatch to
        # within the acceptance tolerance (5%)
        assert row["wall_s"] == pytest.approx(wall_measured, rel=0.05)
        assert row["phases"]["idle"] > 0
        assert row["phases"]["compute"] == pytest.approx(0.02)

    def test_local_excludes_barrier_phases(self):
        led = StepLedger()
        led.tick(0)
        led.record("compute", 0.01)
        for p in BARRIER_PHASES:
            led.record(p, 0.02)
        time.sleep(0.12)
        row = led.tick(1)
        expected = row["wall_s"] - 0.02 * len(BARRIER_PHASES)
        assert row["local_s"] == pytest.approx(expected, rel=1e-6)

    def test_idle_clamped_when_phases_overlap_wall(self):
        led = StepLedger()
        led.tick(0)
        # an off-main-thread heal can record more than the interval wall
        led.record("heal", 60.0)
        row = led.tick(1)
        assert row["phases"].get("idle", 0.0) == 0.0  # zero phases elided
        assert row["local_s"] == 0.0  # clamped, never negative

    def test_first_tick_returns_none(self):
        led = StepLedger()
        assert led.tick(0) is None

    def test_summary_percentiles_are_exact(self):
        led = StepLedger()
        led.tick(0)
        walls = []
        for i in range(5):
            led.record("compute", 0.001 * (i + 1))
            time.sleep(0.01)
            walls.append(led.tick(i + 1)["wall_s"])
        s = led.summary()
        walls.sort()
        assert s["steps"] == 5
        # exact interpolated median of the retained rows, not a
        # log2-bucket estimate (one bucket per octave would be +-50%)
        assert s["wall_p50_s"] == pytest.approx(walls[2], abs=1e-5)
        assert s["phases"]["compute"]["p50_s"] == pytest.approx(0.003)

    def test_every_phase_observed_every_step(self):
        led = StepLedger()
        led.tick(0)
        led.record("compute", 0.01)
        led.tick(1)
        for phase in PHASES:
            child = telemetry.STEP_PHASE_SECONDS.labels(phase=phase)
            assert child.count == 1, phase  # zeros observed for inactive

    def test_local_p50_rolls_with_window(self):
        led = StepLedger(window=4)
        led.tick(0)
        for i in range(8):
            time.sleep(0.005)
            led.tick(i + 1)
        assert led.local_p50() is not None
        assert len(led.dump()["rows"]) == 4


class TestWireStageShim:
    def test_shim_feeds_ledger_and_metric(self):
        from torchft_tpu.collectives import (
            record_wire_stage,
            wire_stage_snapshot,
        )

        wire_stage_snapshot(reset=True)
        before = telemetry.WIRE_STAGE_SECONDS.labels(stage="wire").value
        record_wire_stage("wire", 0.25)
        snap = wire_stage_snapshot()
        assert snap["wire"] == pytest.approx(0.25)
        after = telemetry.WIRE_STAGE_SECONDS.labels(stage="wire").value
        assert after - before == pytest.approx(0.25)
        # reset moves the mark; the ledger totals stay monotonic
        wire_stage_snapshot(reset=True)
        assert wire_stage_snapshot() == {}
        record_wire_stage("wire", 0.1)
        assert wire_stage_snapshot()["wire"] == pytest.approx(0.1)

    def test_op_thread_wire_stays_out_of_the_step_row(self):
        """An op-thread record_wire_stage feeds the wire totals but NOT
        the step row (it overlaps the main thread's wall clock); a
        main-thread record feeds both."""
        from torchft_tpu.collectives import (
            record_wire_stage,
            wire_stage_snapshot,
        )

        wire_stage_snapshot(reset=True)
        led = telemetry.LEDGER
        led.tick(0)
        t = threading.Thread(
            target=record_wire_stage, args=("wire", 0.5), name="tft_test_op"
        )
        t.start()
        t.join()
        record_wire_stage("wire", 0.125)
        row = led.tick(1)
        assert wire_stage_snapshot()["wire"] == pytest.approx(0.625)
        assert row["phases"].get("wire", 0.0) == pytest.approx(0.125)

    def test_crossgroup_bench_reader_unchanged(self):
        # the crossgroup bench protocol: reset, run, read per-stage totals
        from torchft_tpu.collectives import (
            WIRE_STAGES,
            record_wire_stage,
            wire_stage_snapshot,
        )

        wire_stage_snapshot(reset=True)
        for s in WIRE_STAGES:
            record_wire_stage(s, 0.01)
        snap = wire_stage_snapshot()
        assert set(snap) == set(WIRE_STAGES)


class TestOutlierSurfacing:
    def test_outlier_digest_in_summary_and_flight_dump(self, tmp_path,
                                                       monkeypatch):
        from torchft_tpu.profiling import StepTimer

        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        t = StepTimer(record_metrics=False)
        t.tick()
        t.mark_heal()
        time.sleep(0.01)
        t.tick()
        assert t.outlier_digest() and t.outlier_digest()[0]["tags"] == ["heal"]
        led = telemetry.LEDGER
        led.attach_timer(t)
        led.tick(0)
        time.sleep(0.005)
        led.tick(1)
        assert led.summary()["outliers"][0]["tags"] == ["heal"]
        # ONE handler, one evidence dir: the flight dump embeds the ledger
        path = telemetry.FLIGHT.dump("manual", force=True)
        assert path is not None and path.startswith(str(tmp_path))
        with open(path) as f:
            payload = json.load(f)
        assert "anatomy" in payload
        assert payload["anatomy"]["rows"], payload["anatomy"]
        assert payload["anatomy"]["summary"]["outliers"][0]["tags"] == ["heal"]


# ---------------------------------------------------------------------------
# native latency histograms
# ---------------------------------------------------------------------------

_CHILD_SNIPPET = """
import json, sys
from torchft_tpu import _native
h, addr = _native.store_create("[::]:0")
c = _native.NativeClient("tcp://" + addr, 5000)
for i in range(int(sys.argv[1])):
    c.call("store.set", {"k": "k%d" % i, "v": b"x"}, 5000)
c.close()
print(json.dumps(_native.lathist_snapshot()))
_native.store_shutdown(h)
"""


class TestNativeLathist:
    def test_bounds_match_python_grid(self):
        from torchft_tpu import _native

        assert tuple(_native.LATHIST_BOUNDS_S) == LOG2_BUCKETS

    def test_snapshot_shape(self):
        from torchft_tpu import _native

        snap = _native.lathist_snapshot()
        assert set(snap) == {
            "dp.hop", "dp.stripe", "rpc.serve", "quorum.fanout"
        }
        for h in snap.values():
            assert len(h["counts"]) == len(LOG2_BUCKETS) + 1  # + overflow
            assert h["count"] == sum(h["counts"])

    def test_merge_exactness_across_two_processes(self):
        """Two processes record independently on the identical fixed
        grid; merging is elementwise integer addition — counts, count and
        sum_ns all add exactly, and the merged quantile is well-defined."""
        from torchft_tpu import _native

        _native.lathist_reset()
        h, addr = _native.store_create("[::]:0")
        try:
            c = _native.NativeClient("tcp://" + addr, 5000)
            for i in range(7):
                c.call("store.set", {"k": f"p{i}", "v": b"x"}, 5000)
            c.close()
        finally:
            _native.store_shutdown(h)
        mine = _native.lathist_snapshot()
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_SNIPPET, "5"],
            capture_output=True, text=True, check=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        theirs = json.loads(out.stdout.strip().splitlines()[-1])
        merged = merge_lathist(mine, theirs)
        for op in merged:
            assert merged[op]["count"] == (
                mine[op]["count"] + theirs[op]["count"]
            )
            assert merged[op]["sum_ns"] == (
                mine[op]["sum_ns"] + theirs[op]["sum_ns"]
            )
            assert merged[op]["counts"] == [
                a + b
                for a, b in zip(mine[op]["counts"], theirs[op]["counts"])
            ]
        serve = merged["rpc.serve"]
        # at least the 7+5 sets plus each client's handshake-adjacent ops
        assert serve["count"] >= 12
        q = lathist_quantile(serve, 0.5)
        assert 0 < q < 1.0  # RPC serves are far under a second

    def test_lighthouse_scrapes_latency(self):
        """The acceptance surface: native latency histograms are
        scrapeable on the lighthouse /metrics, and /status.json carries
        the raw mergeable counts."""
        from torchft_tpu.coordination import LighthouseClient, LighthouseServer

        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            cli = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            cli.heartbeat("repX")
            cli.close()
            with urllib.request.urlopen(
                lh.address() + "/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert 'torchft_latency_seconds_bucket{op="rpc.serve",le="+Inf"}' \
                in text
            assert "torchft_latency_seconds_count" in text
            with urllib.request.urlopen(
                lh.address() + "/status.json", timeout=5
            ) as r:
                status = json.loads(r.read().decode())
            lat = status["latency"]
            assert lat["rpc.serve"]["count"] >= 1
            assert len(lat["rpc.serve"]["counts"]) == len(LOG2_BUCKETS) + 1
            assert lat["rpc.serve"]["p50_s"] > 0
        finally:
            lh.shutdown()


# ---------------------------------------------------------------------------
# piggyback round-trip
# ---------------------------------------------------------------------------


class TestPiggybackRoundTrip:
    def test_anatomy_scalars_reach_cluster_json(self):
        from torchft_tpu.coordination import LighthouseClient, LighthouseServer
        from torchft_tpu.telemetry.native import poll_cluster

        payload = {
            "summary": json.dumps({"quorums": 1}),
            "anatomy": json.dumps(
                {"steps": 3, "phases": {"compute": {"p50_s": 0.01}}}
            ),
            "local_step_p50_s": 0.125,
            "slo_breach": True,
            "step": 3,
            "stuck": False,
            "last_heal_ts": 0.0,
        }
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            cli = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            cli.heartbeat("repA", telemetry_payload=payload)
            cli.heartbeat("repB", telemetry_payload={"step": 2})
            cli.close()
            cluster = poll_cluster(lh.address())
            assert cluster is not None
            a = cluster["replicas"]["repA"]
            assert a["local_step_p50_s"] == pytest.approx(0.125)
            assert a["slo_breach"] is True
            assert a["anatomy"]["steps"] == 3
            assert a["anatomy"]["phases"]["compute"]["p50_s"] == 0.01
            b = cluster["replicas"]["repB"]
            assert b["slo_breach"] is False
            assert b["anatomy"] == {}
            # the /metrics scalars next to it
            with urllib.request.urlopen(
                lh.address() + "/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert 'torchft_replica_local_step_p50_seconds{replica_id="repA"} 0.125' in text
            assert 'torchft_slo_breach{replica_id="repA"} 1' in text
        finally:
            lh.shutdown()

    def test_manager_payload_carries_anatomy(self):
        """The Manager's piggyback builder includes the new fields (unit:
        the payload shape, not a live quorum — the round trip above and
        the integration soaks cover the wire)."""
        led = telemetry.LEDGER
        led.tick(0)
        time.sleep(0.005)
        led.tick(1)
        import json as _json

        payload = {
            "anatomy": _json.dumps(led.summary(), separators=(",", ":")),
            "local_step_p50_s": float(led.local_p50() or 0.0),
        }
        assert payload["local_step_p50_s"] > 0
        assert _json.loads(payload["anatomy"])["steps"] == 1


# ---------------------------------------------------------------------------
# burn-rate SLO math
# ---------------------------------------------------------------------------


class TestBurnRateSlo:
    def mk(self, **kw):
        kw.setdefault("target", 0.9)       # budget 0.1
        kw.setdefault("fast_s", 10.0)
        kw.setdefault("slow_s", 100.0)
        kw.setdefault("burn", 2.0)
        kw.setdefault("min_events", 2)
        return BurnRateSlo("step_time", 1.0, **kw)

    def test_no_breach_under_budget(self):
        s = self.mk()
        now = 0.0
        for v in [0.5] * 20:
            now += 1
            assert s.observe(v, now=now) is False

    def test_breach_requires_both_windows(self):
        # bad events ONLY in the fast window: slow window burn stays under
        # threshold -> no breach (the blip-suppression property)
        s = self.mk(target=0.5, burn=1.5)  # budget 0.5
        now = 0.0
        for _ in range(80):                # old good events fill slow window
            now += 1
            s.observe(0.5, now=now)
        # now a burst of bad events: fast window (last 10) goes 100% bad
        # (burn 2.0 > 1.5) but the slow window is 10/90 bad (~0.22 burn)
        for _ in range(10):
            now += 1
            s.observe(5.0, now=now)
        assert s.breached is False

    def test_breach_and_single_latch(self):
        s = self.mk()
        telemetry.reset()
        now = 0.0
        for _ in range(8):
            now += 1
            s.observe(5.0, now=now)        # 100% bad: burn 10 > 2 everywhere
        assert s.breached is True
        assert s.breaches == 1             # latched once, not per event
        events = telemetry.EVENTS.recent("slo_breach")
        assert len(events) == 1
        assert events[0]["slo"] == "step_time"
        assert telemetry.SLO_BREACH_TOTAL.labels(slo="step_time").value == 1

    def test_recovery_unlatches_and_emits(self):
        s = self.mk()
        now = 0.0
        for _ in range(8):
            now += 1
            s.observe(5.0, now=now)
        assert s.breached
        now += 50.0                        # bad events age out of fast window
        for _ in range(5):
            now += 1
            s.observe(0.5, now=now)
        assert s.breached is False
        assert len(telemetry.EVENTS.recent("slo_recovered")) == 1

    def test_min_events_guard(self):
        s = self.mk(min_events=5)
        assert s.observe(99.0, now=1.0) is False  # one bad sample: no alarm


# ---------------------------------------------------------------------------
# straggler latch/unlatch hysteresis
# ---------------------------------------------------------------------------


class TestStragglerDetector:
    def test_latch_after_k_and_exactly_one_event(self):
        d = StragglerDetector(factor=1.5, k=3)
        evs = []
        for _ in range(6):
            evs += d.update({"g0": 0.1, "g1": 0.1, "g2": 0.5})
        assert d.stragglers() == ["g2"]
        latched = [e for e in evs if e["event"] == "straggler_detected"]
        assert len(latched) == 1
        assert latched[0]["group"] == "g2"
        assert len(telemetry.EVENTS.recent("straggler_detected")) == 1
        assert (
            telemetry.STRAGGLER_DETECTED.labels(group="g2").value == 1
        )
        assert telemetry.STRAGGLERS.value == 1

    def test_consecutive_required(self):
        d = StragglerDetector(factor=1.5, k=3)
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g0": 0.1, "g1": 0.1})   # breaks the streak
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g0": 0.1, "g1": 0.5})
        assert d.stragglers() == []

    def test_unlatch_hysteresis(self):
        d = StragglerDetector(factor=1.5, k=2)
        for _ in range(2):
            d.update({"g0": 0.1, "g1": 0.5})
        assert d.stragglers() == ["g1"]
        # in the dead band (over 0.8*factor=1.2x, under 1.5x): stays latched
        for _ in range(4):
            d.update({"g0": 0.1, "g1": 0.13})
        assert d.stragglers() == ["g1"]
        # clearly back to fleet speed for K consecutive: unlatches
        evs = []
        for _ in range(2):
            evs += d.update({"g0": 0.1, "g1": 0.1})
        assert d.stragglers() == []
        assert [e["event"] for e in evs] == ["straggler_cleared"]
        assert telemetry.STRAGGLERS.value == 0

    def test_gap_breaks_the_consecutive_streak(self):
        """A group absent from a round (manager restart → p50 reports 0)
        must reset its over/under streaks: K means K CONSECUTIVE live
        observations, never K jittery samples separated by gaps."""
        d = StragglerDetector(factor=1.5, k=3)
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g0": 0.1, "g1": 0.0})   # g1 absent (restarting)
        d.update({"g0": 0.1, "g1": 0.5})   # streak restarted, not 3rd hit
        assert d.stragglers() == []
        # an under-min-groups round breaks every streak the same way
        d.update({"g0": 0.1, "g1": 0.5})
        d.update({"g1": 0.5})              # fleet too small: no round
        d.update({"g0": 0.1, "g1": 0.5})
        assert d.stragglers() == []

    def test_merge_accepts_status_json_shape(self):
        # the lighthouse /status.json "latency" entries carry sum_s, the
        # ctypes snapshot sum_ns — merge_lathist must take either
        a = {"rpc.serve": {"counts": [1, 2], "count": 3, "sum_ns": 1500}}
        b = {"rpc.serve": {"counts": [2, 0], "count": 2, "sum_s": 2e-6,
                           "p50_s": 1e-6}}
        m = merge_lathist(a, b)
        assert m["rpc.serve"]["counts"] == [3, 2]
        assert m["rpc.serve"]["count"] == 5
        assert m["rpc.serve"]["sum_ns"] == 1500 + 2000

    def test_min_groups_guard(self):
        d = StragglerDetector(factor=1.5, k=1, min_groups=2)
        assert d.update({"only": 9.0}) == []
        assert d.stragglers() == []

    def test_leave_one_out_baseline(self):
        # with 2 groups each is compared against the OTHER: the fast
        # group must never latch just because the straggler drags a
        # plain fleet median up
        d = StragglerDetector(factor=1.5, k=2)
        for _ in range(4):
            d.update({"fast": 0.1, "slow": 0.9})
        assert d.stragglers() == ["slow"]
