"""Tests for the static-analysis suite (``python -m torchft_tpu.analysis``).

Two halves:

* **fixture tests** — each seeded-bug file under ``tests/fixtures/analysis``
  must be caught by exactly the rule it seeds, and the ``clean.py`` twin
  must pass every rule (the analyzers are themselves code under test);
* **the repo gate** — the real tree must come out clean (0 active
  findings, 0 stale suppressions) through the same entry point CI runs.
  This is the thin tier-1 wrapper the doc-drift checks moved into when
  they left ``test_tracing.py``.
"""

import glob
import json
import os
import re
import subprocess
import sys

import pytest

from torchft_tpu.analysis import Baseline, run_all
from torchft_tpu.analysis import concurrency, docdrift, nativelint, wiredrift
from torchft_tpu.analysis.__main__ import main as analysis_main
from torchft_tpu.analysis.base import Finding

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def _fixture_findings(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return concurrency.analyze_source(name, f.read())


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# concurrency lint fixtures
# ---------------------------------------------------------------------------


class TestConcurrencyFixtures:
    def test_lock_inversion_caught(self):
        finds = _fixture_findings("lock_inversion.py")
        assert "lock-order-cycle" in _rules(finds)
        (f,) = [f for f in finds if f.rule == "lock-order-cycle"]
        assert "self._a" in f.symbol and "self._b" in f.symbol

    def test_blocking_under_lock_caught(self):
        finds = _fixture_findings("blocking_under_lock.py")
        hits = [f for f in finds if f.rule == "blocking-under-lock"]
        assert hits and "sleep" in hits[0].symbol

    def test_callback_under_lock_caught(self):
        finds = _fixture_findings("callback_under_lock.py")
        hits = [f for f in finds if f.rule == "callback-under-lock"]
        assert hits and "set_exception" in hits[0].symbol

    def test_missing_guarded_by_caught(self):
        finds = _fixture_findings("missing_guarded_by.py")
        hits = [f for f in finds if f.rule == "unguarded-shared-write"]
        assert [f.symbol for f in hits] == ["Unguarded._n"]

    def test_guard_not_held_caught(self):
        finds = _fixture_findings("guard_not_held.py")
        hits = [f for f in finds if f.rule == "guard-not-held"]
        assert len(hits) == 1
        assert hits[0].symbol == "BadGuard._n@bump"
        # the annotated, locked write is NOT flagged
        assert not [f for f in finds if f.rule == "unguarded-shared-write"]

    def test_cond_wait_no_loop_caught(self):
        finds = _fixture_findings("cond_wait_no_loop.py")
        assert "cond-wait-no-loop" in _rules(finds)

    def test_unnamed_thread_caught(self):
        finds = _fixture_findings("unnamed_thread.py")
        assert "thread-unnamed" in _rules(finds)

    def test_clean_fixture_passes_every_rule(self):
        finds = _fixture_findings("clean.py")
        assert finds == [], [f.render() for f in finds]

    def test_runtime_modules_all_parse(self):
        """The gate actually covers the whole ISSUE module list."""
        for rel in concurrency.RUNTIME_MODULES:
            assert os.path.exists(os.path.join(REPO, rel)), rel


# ---------------------------------------------------------------------------
# wire-drift fixtures
# ---------------------------------------------------------------------------


class TestWireDriftFixtures:
    def _texts(self):
        with open(os.path.join(FIXTURES, "wire_mismatch.h")) as f:
            hdr = f.read()
        with open(os.path.join(FIXTURES, "wire_mismatch_py.txt")) as f:
            py = f.read()
        return hdr, py

    def test_cpp_python_mismatch_caught(self):
        hdr, py = self._texts()
        finds = wiredrift.check_wire_tags(hdr, py)
        by_symbol = {f.symbol: f for f in finds}
        # STR exists only in the header
        assert "STR" in by_symbol
        assert "missing" in by_symbol["STR"].message
        # F64 value disagrees (2 vs 7)
        assert "F64" in by_symbol
        assert "mismatch" in by_symbol["F64"].message
        # NIL/I64 agree
        assert "NIL" not in by_symbol and "I64" not in by_symbol

    def test_matching_sides_pass(self):
        hdr, _ = self._texts()
        py = "_NIL = 0\n_I64 = 1\n_F64 = 2\n_STR = 3\n"
        assert wiredrift.check_wire_tags(hdr, py) == []

    def test_enum_scrape_implicit_values(self):
        got = wiredrift.scrape_cpp_enum(
            "enum class E { A = 3, B, C = 9, D };", "E"
        )
        assert got == {"A": 3, "B": 4, "C": 9, "D": 10}

    def test_wire_env_drift_both_directions(self):
        # code reads CODEC (documented) and GHOST (undocumented); the doc
        # additionally promises STALE, which nothing reads
        py = {
            "a.py": 'os.environ.get("TORCHFT_WIRE_CODEC")\n'
                    'os.environ.get("TORCHFT_WIRE_GHOST")\n',
        }
        doc = (
            "| knob | default |\n"
            "| `TORCHFT_WIRE_CODEC` | f32 |\n"
            "| `TORCHFT_WIRE_STALE` | 1 |\n"
        )
        finds = wiredrift.check_wire_env(py, doc)
        msgs = {f.symbol: f.message for f in finds}
        assert "TORCHFT_WIRE_GHOST" in msgs
        assert "missing from" in msgs["TORCHFT_WIRE_GHOST"]
        assert "TORCHFT_WIRE_STALE" in msgs
        assert "no code reads" in msgs["TORCHFT_WIRE_STALE"]
        assert "TORCHFT_WIRE_CODEC" not in msgs

    def test_wire_env_clean_tree(self):
        # the live repo's TORCHFT_WIRE_* knob family must match the
        # docs/wire_plane.md registry exactly (the PR 6 satellite)
        finds = [f for f in wiredrift.run() if f.rule == "wire-env-drift"]
        assert finds == []

    def test_heal_env_drift_both_directions(self):
        # code reads SOURCES (documented) and GHOST (undocumented); the
        # doc additionally promises STALE, which nothing reads
        py = {
            "a.py": 'os.environ.get("TORCHFT_HEAL_SOURCES")\n'
                    'os.environ.get("TORCHFT_HEAL_GHOST")\n',
        }
        doc = (
            "| knob | default |\n"
            "| `TORCHFT_HEAL_SOURCES` | 4 |\n"
            "| `TORCHFT_HEAL_STALE` | 1 |\n"
        )
        finds = wiredrift.check_heal_env(py, doc)
        msgs = {f.symbol: f.message for f in finds}
        assert "TORCHFT_HEAL_GHOST" in msgs
        assert "missing from" in msgs["TORCHFT_HEAL_GHOST"]
        assert "TORCHFT_HEAL_STALE" in msgs
        assert "no code reads" in msgs["TORCHFT_HEAL_STALE"]
        assert "TORCHFT_HEAL_SOURCES" not in msgs

    def test_heal_env_clean_tree(self):
        # the live repo's TORCHFT_HEAL_* knob family must match the
        # docs/heal_plane.md registry exactly (the ISSUE 9 satellite)
        finds = [f for f in wiredrift.run() if f.rule == "heal-env-drift"]
        assert finds == []

    def test_obs_env_covers_tsdb_and_regression_families(self):
        # the ISSUE 11 satellite: the obs-env-drift rule must enforce the
        # new TORCHFT_TSDB_* / TORCHFT_REGRESSION_* families in BOTH
        # directions, like the SLO/straggler families before them
        py = {
            "a.py": 'os.environ.get("TORCHFT_TSDB_RETAIN")\n'
                    'os.environ.get("TORCHFT_TSDB_GHOST")\n'
                    'os.environ.get("TORCHFT_REGRESSION_DELTA")\n'
                    'os.environ.get("TORCHFT_REGRESSION_GHOST")\n',
        }
        doc = (
            "| knob | default |\n"
            "| `TORCHFT_TSDB_RETAIN` | 512 |\n"
            "| `TORCHFT_TSDB_STALE` | 1 |\n"
            "| `TORCHFT_REGRESSION_DELTA` | 0.05 |\n"
            "| `TORCHFT_REGRESSION_STALE` | 1 |\n"
        )
        finds = wiredrift.check_obs_env(py, doc)
        msgs = {f.symbol: f.message for f in finds}
        for ghost in ("TORCHFT_TSDB_GHOST", "TORCHFT_REGRESSION_GHOST"):
            assert ghost in msgs and "missing from" in msgs[ghost]
        for stale in ("TORCHFT_TSDB_STALE", "TORCHFT_REGRESSION_STALE"):
            assert stale in msgs and "no code reads" in msgs[stale]
        assert "TORCHFT_TSDB_RETAIN" not in msgs
        assert "TORCHFT_REGRESSION_DELTA" not in msgs

    def test_obs_env_covers_prof_and_diag_families(self):
        # the ISSUE 12 satellite: the obs-env-drift rule must enforce
        # the TORCHFT_PROF_* / TORCHFT_DIAG_* families in BOTH
        # directions, like the six families before them
        py = {
            "a.py": 'os.environ.get("TORCHFT_PROF_HZ")\n'
                    'os.environ.get("TORCHFT_PROF_GHOST")\n'
                    'os.environ.get("TORCHFT_DIAG_DIR")\n'
                    'os.environ.get("TORCHFT_DIAG_GHOST")\n',
        }
        doc = (
            "| knob | default |\n"
            "| `TORCHFT_PROF_HZ` | 11 |\n"
            "| `TORCHFT_PROF_STALE` | 1 |\n"
            "| `TORCHFT_DIAG_DIR` | unset |\n"
            "| `TORCHFT_DIAG_STALE` | 1 |\n"
        )
        finds = wiredrift.check_obs_env(py, doc)
        msgs = {f.symbol: f.message for f in finds}
        for ghost in ("TORCHFT_PROF_GHOST", "TORCHFT_DIAG_GHOST"):
            assert ghost in msgs and "missing from" in msgs[ghost]
        for stale in ("TORCHFT_PROF_STALE", "TORCHFT_DIAG_STALE"):
            assert stale in msgs and "no code reads" in msgs[stale]
        assert "TORCHFT_PROF_HZ" not in msgs
        assert "TORCHFT_DIAG_DIR" not in msgs

    def test_obs_env_clean_tree(self):
        # the live repo's observability knob families (SLO / straggler /
        # blackbox / divergence / tsdb / regression / prof / diag) must
        # match the docs/observability.md registries exactly
        finds = [f for f in wiredrift.run() if f.rule == "obs-env-drift"]
        assert finds == []


# ---------------------------------------------------------------------------
# doc-drift fixtures
# ---------------------------------------------------------------------------


class TestDocDriftFixtures:
    DOC = (
        "## Metrics\n"
        "| `tft_ok_total` | counter |\n"
        "| `tft_ghost_total` | counter |\n"
    )

    def test_doc_only_and_code_only_both_flagged(self):
        finds = docdrift.check_metric_catalog(
            self.DOC, {"tft_ok_total", "tft_unseen_total"}
        )
        msgs = {f.symbol: f.message for f in finds}
        assert "tft_ghost_total" in msgs  # documented, not registered
        assert "tft_unseen_total" in msgs  # registered, not documented
        assert "tft_ok_total" not in msgs

    def test_fault_site_doc_table(self):
        doc = "## Site catalog\n| `rpc.send` | x |\n| `ghost.site` | x |\n"
        finds = docdrift.check_fault_sites_doc(doc, ("rpc.send", "cma.pull"))
        symbols = {f.symbol for f in finds}
        assert symbols == {"ghost.site", "cma.pull"}


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------


class TestBaseline:
    def _finding(self):
        return Finding("blocking-under-lock", "x.py", 3, "C.m:sleep", "msg")

    def test_suppression_matches_by_key_not_line(self):
        f = self._finding()
        bl = Baseline(suppressions=[{"key": f.key, "reason": "intentional"}])
        active, suppressed, stale = bl.apply([f])
        assert active == [] and suppressed == [f] and stale == []
        # line number changes do not churn the baseline
        f2 = Finding(f.rule, f.path, 99, f.symbol, f.message)
        active, suppressed, stale = bl.apply([f2])
        assert active == [] and stale == []

    def test_stale_suppression_is_an_error(self, tmp_path):
        """A baseline entry that no longer fires must fail the gate."""
        f = self._finding()
        bl = Baseline(suppressions=[
            {"key": f.key, "reason": "live"},
            {"key": "blocking-under-lock:gone.py:C.x:sleep",
             "reason": "the code this matched was deleted"},
        ])
        active, suppressed, stale = bl.apply([f])
        assert active == []
        assert [e["key"] for e in stale] == [
            "blocking-under-lock:gone.py:C.x:sleep"
        ]
        # end to end: the CLI exits 1 on the stale entry even though the
        # tree itself is clean
        path = tmp_path / "baseline.json"
        real = Baseline.load(
            os.path.join(REPO, "torchft_tpu", "analysis", "baseline.json")
        )
        doc = {"suppressions": real.suppressions + [
            {"key": "blocking-under-lock:gone.py:C.x:sleep",
             "reason": "stale on purpose"},
        ]}
        path.write_text(json.dumps(doc))
        assert analysis_main(["--baseline", str(path)]) == 1

    def test_baseline_entries_require_reason(self, tmp_path):
        path = tmp_path / "b.json"
        path.write_text(json.dumps({"suppressions": [{"key": "x"}]}))
        with pytest.raises(ValueError):
            Baseline.load(str(path))


# ---------------------------------------------------------------------------
# the repo gate (tier-1 wrapper)
# ---------------------------------------------------------------------------


class TestRepoGate:
    def test_gate_clean_in_process(self):
        """0 active findings, 0 stale suppressions on the real tree, via
        the same code path as the CLI."""
        per_analyzer = run_all()
        baseline = Baseline.load(
            os.path.join(REPO, "torchft_tpu", "analysis", "baseline.json")
        )
        allf = [f for finds in per_analyzer.values() for f in finds]
        active, _suppressed, stale = baseline.apply(allf)
        assert active == [], [f.render() for f in active]
        assert stale == [], [e["key"] for e in stale]
        # every suppression carries a real justification
        for e in baseline.suppressions:
            assert e["reason"] and "TODO" not in e["reason"]

    def test_cli_exit_code_and_json(self):
        """`python -m torchft_tpu.analysis --json` — the exact CI
        invocation — exits 0 and reports ok=true."""
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis", "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True
        assert set(doc["analyzers"]) == {"concurrency", "wiredrift",
                                         "docdrift", "nativelint"}


# ---------------------------------------------------------------------------
# native lint fixtures (ISSUE 15)
# ---------------------------------------------------------------------------


def _native_fixture_findings(*names):
    sources = []
    for name in names:
        with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
            sources.append((name, f.read()))
    return nativelint.analyze_sources(sources)


class TestNativeLintFixtures:
    def test_lock_order_cycle_caught(self):
        finds = _native_fixture_findings("lock_cycle.cc")
        hits = [f for f in finds if f.rule == "cpp-lock-order-cycle"]
        assert hits, [f.render() for f in finds]
        # the cycle names both mutexes, and the cross-function edge
        # (push -> refill propagation) is what closes it
        assert "mu_a_" in hits[0].symbol and "mu_b_" in hits[0].symbol

    def test_blocking_under_lock_caught(self):
        finds = _native_fixture_findings("blocking_lock.cc")
        hits = [f for f in finds if f.rule == "cpp-blocking-under-lock"]
        assert [f.symbol for f in hits] == ["Server::reply_locked:send"]

    def test_cv_wait_no_loop_caught(self):
        finds = _native_fixture_findings("blocking_lock.cc")
        hits = [f for f in finds if f.rule == "cpp-cv-wait-no-loop"]
        assert len(hits) == 1 and "wait_bad" in hits[0].symbol
        # the predicate-overload twin is NOT flagged
        assert not [f for f in finds if "wait_ok" in f.symbol]

    def test_unannotated_relaxed_atomic_caught(self):
        finds = _native_fixture_findings("relaxed_atomic.h")
        hits = [f for f in finds
                if f.rule == "cpp-atomic-no-order-reason"]
        assert [f.symbol for f in hits] == ["bump_bad:relaxed"]

    def test_clean_native_fixture_passes_every_rule(self):
        finds = _native_fixture_findings("clean_native.cc")
        assert finds == [], [f.render() for f in finds]

    def test_makefile_hdrs_drift_fixture(self):
        with open(os.path.join(FIXTURES, "makefile_hdrs_drift.mk")) as f:
            mk = f.read()
        finds = wiredrift.check_makefile_hdrs(
            mk, ["wire.h", "rpc.h", "newthing.h"]
        )
        by_symbol = {f.symbol: f.message for f in finds}
        assert set(by_symbol) == {"newthing.h", "gone.h"}
        assert "stale" in by_symbol["newthing.h"]
        assert "does not exist" in by_symbol["gone.h"]

    def test_makefile_hdrs_clean_tree(self):
        """Every real native/*.h is in the real Makefile's HDRS."""
        finds = [
            f for f in wiredrift.run()
            if f.rule == "makefile-hdrs-drift"
        ]
        assert finds == [], [f.render() for f in finds]

    def test_native_tree_lints_clean_through_baseline(self):
        """The real native tree: every finding baselined, none active
        (the repo-gate test covers this too; this one names the
        analyzer so a nativelint regression reads as itself)."""
        finds = nativelint.run()
        baseline = Baseline.load(
            os.path.join(REPO, "torchft_tpu", "analysis", "baseline.json")
        )
        active, _suppressed, _stale = baseline.apply(finds)
        assert active == [], [f.render() for f in active]


# ---------------------------------------------------------------------------
# premerge gate-id drift (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


class TestPremergeGateDrift:
    DOC = (
        "### Pre-merge gates\n\nprose\n\n"
        "| gate | what runs |\n"
        "|---|---|\n"
        "| `analysis` | x |\n"
        "| `ghost-gate` | x |\n"
    )
    SCRIPT = (
        'record_gate "analysis" passed 1\n'
        '  record_gate "native-warn" skipped 0\n'
    )

    def test_both_directions_flagged(self):
        finds = docdrift.check_premerge_gates(self.DOC, self.SCRIPT)
        msgs = {f.symbol: f.message for f in finds}
        # documented but never recorded; recorded but undocumented
        assert "ghost-gate" in msgs and "no record_gate" in msgs["ghost-gate"]
        assert "native-warn" in msgs and "missing from" in msgs["native-warn"]
        assert "analysis" not in msgs

    def test_missing_table_is_a_finding(self):
        finds = docdrift.check_premerge_gates("# no section\n", self.SCRIPT)
        assert [f.symbol for f in finds] == ["<table>"]

    def test_missing_record_sites_is_a_finding(self):
        finds = docdrift.check_premerge_gates(self.DOC, "echo hi\n")
        assert [f.symbol for f in finds] == ["<script>"]

    def test_real_script_records_all_six_gates(self):
        """Every gate in premerge.sh emits a --json record — including
        the clang-tidy skip, which must be VISIBLE, not silent."""
        with open(os.path.join(REPO, "scripts", "premerge.sh")) as f:
            ids = set(re.findall(r'record_gate "([a-z0-9-]+)"', f.read()))
        assert ids == {"analysis", "native-warn", "native-tidy",
                       "faultmatrix-quick", "profiler-smoke",
                       "telemetry-smoke", "protocol"}

    def test_clean_tree(self):
        finds = [
            f for f in docdrift.run() if f.rule == "premerge-gate-drift"
        ]
        assert finds == [], [f.render() for f in finds]


# ---------------------------------------------------------------------------
# incremental analysis cache (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


class TestAnalysisCache:
    def test_fingerprint_tracks_edits_and_adds(self, tmp_path):
        from torchft_tpu.analysis.cache import fingerprint

        (tmp_path / "native").mkdir()
        hdr = tmp_path / "native" / "a.h"
        hdr.write_text("int x;\n")
        pats = ("native/*.h",)
        base = fingerprint(str(tmp_path), pats)
        assert fingerprint(str(tmp_path), pats) == base  # deterministic
        hdr.write_text("int y;\n")
        edited = fingerprint(str(tmp_path), pats)
        assert edited != base  # edit -> new digest
        (tmp_path / "native" / "b.h").write_text("")
        assert fingerprint(str(tmp_path), pats) != edited  # add -> new digest

    def test_edit_refires_hit_replays(self, tmp_path):
        """The correctness contract: unchanged inputs -> the stored
        findings replay verbatim; ANY scanned-file edit -> miss."""
        from torchft_tpu.analysis.cache import AnalysisCache

        (tmp_path / "native").mkdir()
        hdr = tmp_path / "native" / "a.h"
        hdr.write_text("// v1\n")
        cache = AnalysisCache(str(tmp_path))
        assert cache.get("nativelint") is None  # cold
        finds = [Finding("cpp-atomic-no-order-reason", "native/a.h", 3,
                         "bump:relaxed", "msg")]
        cache.put("nativelint", finds)
        warm = AnalysisCache(str(tmp_path))
        assert warm.get("nativelint") == finds
        assert warm.hits == ["nativelint"]
        hdr.write_text("// v2\n")
        stale = AnalysisCache(str(tmp_path))
        assert stale.get("nativelint") is None  # edit -> re-fire

    def test_unknown_analyzer_never_caches(self, tmp_path):
        from torchft_tpu.analysis.cache import AnalysisCache

        cache = AnalysisCache(str(tmp_path))
        cache.put("mystery", [])
        assert cache.get("mystery") is None
        assert not (tmp_path / ".analysis_cache" / "mystery.json").exists()

    def test_corrupt_cache_file_is_a_miss(self, tmp_path):
        from torchft_tpu.analysis.cache import AnalysisCache

        (tmp_path / "native").mkdir()
        (tmp_path / "native" / "a.h").write_text("int x;\n")
        cache = AnalysisCache(str(tmp_path))
        cache.put("nativelint", [])
        (tmp_path / ".analysis_cache" / "nativelint.json").write_text("{oops")
        assert AnalysisCache(str(tmp_path)).get("nativelint") is None

    def test_cached_gate_verdict_identical_to_fresh(self):
        """End to end on the real tree: a warm cache replays byte-equal
        finding keys for every analyzer."""
        from torchft_tpu.analysis.cache import AnalysisCache

        cold_cache = AnalysisCache()
        cold = run_all(cache=cold_cache)
        warm_cache = AnalysisCache()
        warm = run_all(cache=warm_cache)
        assert set(warm_cache.hits) == {"concurrency", "wiredrift",
                                        "docdrift", "nativelint"}
        assert warm_cache.misses == []
        for name in cold:
            assert [f.key for f in cold[name]] == \
                [f.key for f in warm[name]], name


# ---------------------------------------------------------------------------
# telemetry_delta.h nativelint pin (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


class TestTelemetryDeltaPin:
    REL = os.path.join("native", "telemetry_delta.h")

    def test_file_is_in_the_scanned_set(self):
        scanned = set()
        for pat in nativelint.NATIVE_GLOBS:
            scanned.update(glob.glob(os.path.join(REPO, pat)))
        assert os.path.join(REPO, self.REL) in scanned

    def test_clean_tree_zero_findings(self):
        """PR 16's delta ledger is mutex-guarded by design — zero atomic
        sites, so zero annotation findings; this pins that a future
        atomic added without a reason lands as an ACTIVE finding."""
        finds = [f for f in nativelint.run() if "telemetry_delta" in f.path]
        assert finds == [], [f.render() for f in finds]

    def test_seeded_unannotated_atomic_fires(self):
        """The pin is only meaningful if the lint would actually catch a
        regression in THIS file: seed one unannotated relaxed op into
        the real source and watch the rule fire."""
        with open(os.path.join(REPO, self.REL), encoding="utf-8") as f:
            src = f.read()
        seeded = src + (
            "\ninline void tdx_bump(std::atomic<unsigned long>& c) {\n"
            "  c.fetch_add(1, std::memory_order_relaxed);\n"
            "}\n"
        )
        finds = nativelint.analyze_sources([("telemetry_delta.h", seeded)])
        hits = [f for f in finds
                if f.rule == "cpp-atomic-no-order-reason"
                and "tdx_bump" in f.symbol]
        assert hits, [f.render() for f in finds]
