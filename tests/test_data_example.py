"""Resume-correctness proof for the real-data example (round-2 review
missing #2): kill a replica group mid-epoch, restart it (disk resume +
live heal), and verify from the committed-step traces that no sample was
double-trained and none skipped — the dataloader position really survives
failure.

Reference behavior being matched: train_ddp.py:34-80's stateful dataloader
(torchdata StatefulDataLoader) position checkpointing."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
from conftest import scaled_timeout

pytestmark = pytest.mark.soak

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")

STEPS = 24
BATCH = 8


def _spawn(gid, lighthouse_addr, tmp, env_extra=None):
    env = dict(os.environ)
    env.update(
        REPLICA_GROUP_ID=str(gid),
        NUM_REPLICA_GROUPS="2",
        STEPS=str(STEPS),
        BATCH=str(BATCH),
        DATA_PATH=os.path.join(tmp, "corpus.bin"),
        TRACE_PATH=os.path.join(tmp, f"trace{gid}.jsonl"),
        CKPT_DIR=os.path.join(tmp, "ckpt"),
        CKPT_EVERY="3",
        TORCHFT_LIGHTHOUSE=lighthouse_addr,
        JAX_PLATFORMS="cpu",
    )
    if env_extra:
        env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, os.path.join(_EXAMPLES, "train_bytes.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _trace_lines(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_kill_restart_no_sample_skipped_or_repeated(tmp_path):
    tmp = str(tmp_path)
    # small real corpus on disk: epochs roll every 2 steps, so the kill is
    # always mid-epoch and resume crosses epoch boundaries repeatedly
    rng = np.random.default_rng(0)
    with open(os.path.join(tmp, "corpus.bin"), "wb") as f:
        f.write(rng.integers(0, 256, 4001, dtype=np.uint8).tobytes())

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {}
    try:
        for g in (0, 1):
            procs[g] = _spawn(g, addr, tmp)

        # wait until the victim has committed a few steps, then SIGKILL
        victim_trace = os.path.join(tmp, "trace1.jsonl")
        deadline = time.time() + 240
        while len(_trace_lines(victim_trace)) < 5:
            assert time.time() < deadline, "victim never made progress"
            assert procs[0].poll() is None and procs[1].poll() is None
            time.sleep(0.5)
        os.kill(procs[1].pid, signal.SIGKILL)
        procs[1].wait()

        # restart: disk-resume + live heal, then run to completion
        procs[1] = _spawn(1, addr, tmp)
        for g in (0, 1):
            out, _ = procs[g].communicate(timeout=scaled_timeout(300))
            assert procs[g].returncode == 0, out.decode()[-2000:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()

    # ---- the proof ----
    sys.path.insert(0, _EXAMPLES)
    from train_bytes import SEQ, batch_indices  # noqa: E402

    from torchft_tpu.data import DistributedSampler

    corpus_len = os.path.getsize(os.path.join(tmp, "corpus.bin"))
    n_windows = (corpus_len - 1) // SEQ

    all_by_step = {}
    for g in (0, 1):
        lines = _trace_lines(os.path.join(tmp, f"trace{g}.jsonl"))
        assert lines, f"group {g} committed nothing"
        steps = [ln["step"] for ln in lines]
        # each committed step logged exactly once — a double-trained batch
        # (resume too early) would duplicate a step; a skipped position
        # would diverge from the oracle below
        assert len(steps) == len(set(steps)), f"group {g} double-trained: {steps}"
        assert steps == sorted(steps)
        sampler = DistributedSampler(
            n_windows, replica_group=g, num_replica_groups=2, shuffle=True, seed=0
        )
        for ln in lines:
            expect = batch_indices(sampler, ln["step"], BATCH)
            assert ln["ids"] == expect.tolist(), (
                f"group {g} step {ln['step']}: trained wrong samples after "
                f"kill/resume (position drift)"
            )
            all_by_step.setdefault(ln["step"], {})[g] = set(ln["ids"])

    # the survivor covered every step; the victim's only gap is its
    # blackout window (contiguous), never interior repeats
    g0_steps = {ln["step"] for ln in _trace_lines(os.path.join(tmp, "trace0.jsonl"))}
    assert g0_steps == set(range(STEPS))

    # same-epoch partitions are disjoint across groups (no cross-group
    # double-training): check every step both groups committed
    for step, by_group in all_by_step.items():
        if len(by_group) == 2:
            assert not (by_group[0] & by_group[1]), f"overlap at step {step}"
