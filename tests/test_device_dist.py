"""CollectivesDeviceDist: 2 replica groups as separate OS PROCESSES
averaging over ONE shared multi-controller JAX runtime — the round-3
review's missing topology (the in-process CollectivesDevice registry
can't span processes; the launcher/k8s put every group in its own).
On real hardware the psum rides ICI; here the runtime is 2 CPU
processes × 2 virtual devices."""

import os
import subprocess
import sys

import pytest

from conftest import scaled_timeout

# multi-process soak tier: excluded from the default run (pyproject addopts)
pytestmark = pytest.mark.soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import numpy as np
from torchft_tpu.collectives import ReduceOp
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_distributed

gid = int(sys.argv[1]); coordinator = sys.argv[2]; out = sys.argv[3]
store_addr = sys.argv[4]
init_distributed(coordinator, 2, gid)
assert jax.process_count() == 2

c = CollectivesDeviceDist()
c.configure(store_addr, gid, 2)

rng = np.random.default_rng(5 + gid)
a = rng.standard_normal(10001).astype(np.float32)
orig = a.copy()
c.allreduce([a], ReduceOp.AVG).wait()

ag = c.allgather(np.full(4, float(gid), np.float32)).wait()
b = np.zeros(3, np.float32) if gid else np.arange(3, dtype=np.float32)
c.broadcast(b, root=0).wait()
c.barrier().wait()

# full op surface (round-4 review missing #2): reduce_scatter and
# alltoall ride the device mesh; send/recv ride the host side-channel
rs = c.reduce_scatter(
    [np.full(5, float(gid + 1), np.float32),
     np.full(5, float(10 * (gid + 1)), np.float32)],
    ReduceOp.SUM,
).wait()  # rank0 owns slot0: 1+2=3; rank1 owns slot1: 10+20=30
a2a = c.alltoall(
    [np.full(2, float(gid * 10 + j), np.float32) for j in range(2)]
).wait()  # rank r receives [0*10+r, 1*10+r]
if gid == 0:
    rbuf = np.zeros(7, np.float32)
    c.recv(rbuf, 1, tag=5).wait()
    c.send(np.full(7, 3.25, np.float32), 1, tag=6).wait()
else:
    c.send(np.full(7, 7.5, np.float32), 0, tag=5).wait()
    rbuf = np.zeros(7, np.float32)
    c.recv(rbuf, 0, tag=6).wait()

# AVG on ints must raise like the host plane's np.divide casting error,
# not silently truncate (round-4 advisor low)
try:
    c.allreduce([np.ones(4, np.int32)], ReduceOp.AVG).wait()
    avg_int = "no-error"
except TypeError:
    avg_int = "raised"

# RAGGED reduce_scatter/alltoall (per-slot shapes) can't stack onto the
# device mesh and must fall back to the p2p side-channel, same results
rrs = c.reduce_scatter(
    [np.full(3 + gid_slot, float(gid + 1), np.float32)
     for gid_slot in range(2)],
    ReduceOp.SUM,
).wait()  # rank r owns slot r (shape 3+r): sum = 1+2 = 3.0
# shapes must be SYMMETRIC (my slot-j shape == rank j's slot-me shape),
# the same contract the host plane's exchange imposes
ra2a = c.alltoall(
    [np.full(2 + gid + j, float(gid * 10 + j), np.float32)
     for j in range(2)]
).wait()  # rank r's out[j]: shape 2+j+r, value j*10+r

# cohort mismatch must raise loudly, not deadlock — including a quorum
# shrunk to ONE on this 2-process runtime (silent singleton no-op
# allreduces would let partitioned groups diverge)
try:
    c.configure("", gid, 3)
    mismatch = "no-error"
except RuntimeError as e:
    mismatch = "raised"
try:
    c.configure("", 0, 1)
    mismatch += "+shrunk-no-error"
except RuntimeError:
    mismatch += "+shrunk-raised"

with open(out, "w") as f:
    json.dump({
        "sum": float(a.sum()), "first": float(a[0]),
        "own_mean_first": float(orig[0]),
        "ag": [float(x[0]) for x in ag],
        "bcast": [float(x) for x in b],
        "rs": [float(x) for x in rs],
        "a2a": [float(x[0]) for x in a2a],
        "p2p": float(rbuf[0]),
        "avg_int": avg_int,
        "ragged_rs": [len(rrs), float(rrs[0])],
        "ragged_a2a": [[len(x), float(x[0])] for x in ra2a],
        "mismatch": mismatch,
    }, f)
"""


def test_two_process_shared_runtime_allreduce(tmp_path):
    from torchft_tpu.launcher import _free_port
    from torchft_tpu.store import StoreServer

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.replace("__REPO__", REPO))
    coordinator = f"localhost:{_free_port()}"
    outs = [str(tmp_path / f"g{g}.json") for g in range(2)]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    store = StoreServer()  # rendezvous for the p2p side-channel
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(worker), str(g), coordinator, outs[g],
                store.address(),
            ],
            env=env,
            cwd=REPO,
        )
        for g in range(2)
    ]
    try:
        for p in procs:
            assert p.wait(timeout=scaled_timeout(120)) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        store.shutdown()

    import json

    import numpy as np

    r0, r1 = (json.load(open(o)) for o in outs)
    # both processes hold the bitwise-identical average
    assert r0["sum"] == r1["sum"]
    assert r0["first"] == r1["first"]
    # and it IS an average of the two inputs, not either one alone
    rng0 = np.random.default_rng(5).standard_normal(10001).astype(np.float32)
    rng1 = np.random.default_rng(6).standard_normal(10001).astype(np.float32)
    np.testing.assert_allclose(
        r0["first"], (rng0[0] + rng1[0]) / 2.0, rtol=1e-6
    )
    assert r0["ag"] == [0.0, 1.0] and r1["ag"] == [0.0, 1.0]
    assert r0["bcast"] == [0.0, 1.0, 2.0] and r1["bcast"] == [0.0, 1.0, 2.0]
    # reduce_scatter: rank r holds sum over contributors of slot r
    assert r0["rs"] == [3.0] * 5 and r1["rs"] == [30.0] * 5, (r0["rs"], r1["rs"])
    # alltoall: rank r receives [sender0's slot r, sender1's slot r]
    assert r0["a2a"] == [0.0, 10.0] and r1["a2a"] == [1.0, 11.0]
    # p2p over the host side-channel (what CollectivesTransport heals use)
    assert r0["p2p"] == 7.5 and r1["p2p"] == 3.25
    assert r0["avg_int"] == "raised" and r1["avg_int"] == "raised"
    # ragged lists fell back to the side-channel with correct results
    assert r0["ragged_rs"] == [3, 3.0] and r1["ragged_rs"] == [4, 3.0]
    assert r0["ragged_a2a"] == [[2, 0.0], [3, 10.0]]
    assert r1["ragged_a2a"] == [[3, 1.0], [4, 11.0]]
    assert r0["mismatch"] == "raised+shrunk-raised", r0["mismatch"]
    assert r1["mismatch"] == "raised+shrunk-raised", r1["mismatch"]


_COHORT_WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import numpy as np
from torchft_tpu.collectives import ReduceOp
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_from_env

marker, outdir = sys.argv[1], sys.argv[2]
gid = int(os.environ["REPLICA_GROUP_ID"])
assert init_from_env(), "cohort env missing"
c = CollectivesDeviceDist()
c.configure("", gid, int(os.environ["NUM_REPLICA_GROUPS"]))
a = np.full(64, float(gid + 1), np.float32)
c.allreduce([a], ReduceOp.AVG).wait()
if gid == 1 and not os.path.exists(marker):
    open(marker, "w").write("died")
    os._exit(1)  # first attempt: die AFTER joining the runtime
with open(os.path.join(outdir, f"g{gid}.json"), "w") as f:
    json.dump({"v": float(a[0])}, f)
"""


def test_shared_runtime_cohort_restart(tmp_path):
    """launcher --shared-runtime semantics: a worker dying after joining
    the multi-controller runtime forces a WHOLE-cohort respawn (fresh
    coordinator), and the respawned cohort completes."""
    import json

    from torchft_tpu.launcher import launch_shared_runtime

    worker = tmp_path / "worker.py"
    worker.write_text(_COHORT_WORKER.replace("__REPO__", REPO))
    marker = tmp_path / "died.marker"
    rc = launch_shared_runtime(
        [sys.executable, str(worker), str(marker), str(tmp_path)],
        num_groups=2,
        max_restarts=2,
    )
    assert rc == 0
    assert marker.exists()  # the first attempt really died
    for g in range(2):
        v = json.load(open(tmp_path / f"g{g}.json"))["v"]
        assert v == 1.5, (g, v)  # avg of 1.0 and 2.0, identical everywhere


_HEAL_WORKER = r"""
import logging, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import json
from datetime import timedelta
import numpy as np
import optax
from torchft_tpu.checkpointing.collectives_transport import CollectivesTransport
from torchft_tpu.checkpointing.disk import DiskCheckpointer
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_from_env
from torchft_tpu.manager import Manager
from torchft_tpu.optim import ManagedOptimizer
from torchft_tpu.store import StoreServer

workdir = sys.argv[1]
gid = int(os.environ["REPLICA_GROUP_ID"])
logging.basicConfig(
    level=logging.INFO,
    filename=os.path.join(workdir, f"g{gid}.log"),  # appends across respawns
)
STEPS = 12
assert init_from_env(), "cohort env missing"
collectives = CollectivesDeviceDist(timeout=timedelta(seconds=30))
store = StoreServer()
manager = Manager(
    collectives=collectives,
    load_state_dict=None,  # wired by ManagedOptimizer.init
    state_dict=None,
    min_replica_size=2,
    replica_id=f"heal_dd_{gid}",
    store_addr=store.address(),
    rank=0,
    world_size=1,
    timeout=timedelta(seconds=30),
    # the point of this test: the heal payload rides the device-dist
    # plane's p2p side-channel, not HTTP
    checkpoint_transport=CollectivesTransport(
        collectives, timeout=timedelta(seconds=30)
    ),
)
rng = np.random.default_rng(3)
x = rng.standard_normal((256, 16)).astype(np.float32)
y = (x.sum(axis=1) > 0).astype(np.int32)

def loss_fn(params, xb, yb):
    logits = xb @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

opt = ManagedOptimizer(manager, optax.adam(1e-2))
opt.init({
    "w": np.zeros((16, 2), np.float32),
    "b": np.zeros(2, np.float32),
})
ckpt = None
if gid == 0:
    # only group 0 persists: after the whole-cohort respawn it restores
    # mid-run progress while group 1 comes back at step 0 and must heal
    ckpt = DiskCheckpointer(
        os.path.join(workdir, "ckpt0"),
        manager,
        state_dict=lambda: {"opt": opt.state_dict()},
        load_state_dict=lambda s: opt.load_state_dict(s["opt"]),
        every=2,
        tag="group0",
        is_writer=True,
    )
    ckpt.restore()
value_and_grad = jax.jit(jax.value_and_grad(loss_fn))
marker = os.path.join(workdir, "died.marker")
import time
prev = manager.current_step()
while manager.current_step() < STEPS:
    idx = rng.integers(0, len(x), 32)
    opt.begin_step()
    loss, grads = value_and_grad(opt.params, x[idx], y[idx])
    opt.step(grads)
    if manager.current_step() == prev:
        time.sleep(0.2)
    prev = manager.current_step()
    if ckpt is not None:
        ckpt.maybe_save()
    if gid == 1 and manager.current_step() >= 5 and not os.path.exists(marker):
        open(marker, "w").write("died")
        os._exit(1)  # SIGKILL-equivalent mid-run; cohort must respawn
checksum = float(
    sum(float(np.asarray(v).sum()) for v in opt.params.values())
)
with open(os.path.join(workdir, f"g{gid}.json"), "w") as f:
    json.dump({"step": manager.current_step(), "checksum": checksum}, f)
manager.shutdown(wait=False)
store.shutdown()
"""


def test_heal_over_device_dist_plane(tmp_path):
    """Round-4 review missing #2 e2e: kill one cohort member under
    --shared-runtime, respawn the cohort, and live-heal the stale group
    over the device-dist plane's CollectivesTransport (p2p side-channel)
    — both groups must finish at the same step with bit-identical
    params, and the heal must actually have run."""
    import json

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.launcher import launch_shared_runtime

    worker = tmp_path / "worker.py"
    worker.write_text(_HEAL_WORKER.replace("__REPO__", REPO))
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    env_save = dict(os.environ)
    os.environ["TORCHFT_LIGHTHOUSE"] = lighthouse.address()
    try:
        rc = launch_shared_runtime(
            [sys.executable, str(worker), str(tmp_path)],
            num_groups=2,
            max_restarts=2,
        )
    finally:
        os.environ.clear()
        os.environ.update(env_save)
        lighthouse.shutdown()
    assert rc == 0
    assert (tmp_path / "died.marker").exists()  # the kill really happened
    r0, r1 = (
        json.load(open(tmp_path / f"g{g}.json")) for g in range(2)
    )
    assert r0["step"] == 12 and r1["step"] == 12, (r0, r1)
    assert r0["checksum"] == r1["checksum"], (r0, r1)
    # group 1's respawn really healed over the collectives transport
    # (it came back at step 0 while group 0 restored mid-run progress)
    g1_log = (tmp_path / "g1.log").read_text()
    assert "healing: fetching checkpoint metadata" in g1_log, g1_log[-2000:]


def test_train_ddp_over_shared_runtime(tmp_path):
    """The full Manager FT loop (quorum + commit + ManagedOptimizer) with
    CollectivesDeviceDist as the data plane: 2 groups under
    launcher --shared-runtime must finish with bit-identical params."""
    import re

    from torchft_tpu.launcher import launch_shared_runtime

    wrapper = tmp_path / "wrap.sh"
    wrapper.write_text(
        "#!/bin/bash\n"
        f"cd {REPO}\n"
        f"exec {sys.executable} examples/train_ddp.py > "
        f"{tmp_path}/g${{REPLICA_GROUP_ID}}.log 2>&1\n"
    )
    wrapper.chmod(0o755)
    env_save = dict(os.environ)
    os.environ.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        DATA_PLANE="device-dist",
        STEPS="20",
    )
    try:
        rc = launch_shared_runtime([str(wrapper)], num_groups=2, max_restarts=1)
    finally:
        os.environ.clear()
        os.environ.update(env_save)
    assert rc == 0
    sums = []
    for g in range(2):
        text = (tmp_path / f"g{g}.log").read_text()
        m = re.findall(r"param_checksum=(-?\d+\.\d+)", text)
        assert m, text[-2000:]
        sums.append(m[-1])
    assert sums[0] == sums[1], sums
