"""CollectivesDeviceDist: 2 replica groups as separate OS PROCESSES
averaging over ONE shared multi-controller JAX runtime — the round-3
review's missing topology (the in-process CollectivesDevice registry
can't span processes; the launcher/k8s put every group in its own).
On real hardware the psum rides ICI; here the runtime is 2 CPU
processes × 2 virtual devices."""

import os
import subprocess
import sys

import pytest

from conftest import scaled_timeout

# multi-process soak tier: excluded from the default run (pyproject addopts)
pytestmark = pytest.mark.soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import numpy as np
from torchft_tpu.collectives import ReduceOp
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_distributed

gid = int(sys.argv[1]); coordinator = sys.argv[2]; out = sys.argv[3]
init_distributed(coordinator, 2, gid)
assert jax.process_count() == 2

c = CollectivesDeviceDist()
c.configure("", gid, 2)

rng = np.random.default_rng(5 + gid)
a = rng.standard_normal(10001).astype(np.float32)
orig = a.copy()
c.allreduce([a], ReduceOp.AVG).wait()

ag = c.allgather(np.full(4, float(gid), np.float32)).wait()
b = np.zeros(3, np.float32) if gid else np.arange(3, dtype=np.float32)
c.broadcast(b, root=0).wait()
c.barrier().wait()

# cohort mismatch must raise loudly, not deadlock — including a quorum
# shrunk to ONE on this 2-process runtime (silent singleton no-op
# allreduces would let partitioned groups diverge)
try:
    c.configure("", gid, 3)
    mismatch = "no-error"
except RuntimeError as e:
    mismatch = "raised"
try:
    c.configure("", 0, 1)
    mismatch += "+shrunk-no-error"
except RuntimeError:
    mismatch += "+shrunk-raised"

with open(out, "w") as f:
    json.dump({
        "sum": float(a.sum()), "first": float(a[0]),
        "own_mean_first": float(orig[0]),
        "ag": [float(x[0]) for x in ag],
        "bcast": [float(x) for x in b],
        "mismatch": mismatch,
    }, f)
"""


def test_two_process_shared_runtime_allreduce(tmp_path):
    from torchft_tpu.launcher import _free_port

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.replace("__REPO__", REPO))
    coordinator = f"localhost:{_free_port()}"
    outs = [str(tmp_path / f"g{g}.json") for g in range(2)]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(g), coordinator, outs[g]],
            env=env,
            cwd=REPO,
        )
        for g in range(2)
    ]
    try:
        for p in procs:
            assert p.wait(timeout=scaled_timeout(120)) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    import json

    import numpy as np

    r0, r1 = (json.load(open(o)) for o in outs)
    # both processes hold the bitwise-identical average
    assert r0["sum"] == r1["sum"]
    assert r0["first"] == r1["first"]
    # and it IS an average of the two inputs, not either one alone
    rng0 = np.random.default_rng(5).standard_normal(10001).astype(np.float32)
    rng1 = np.random.default_rng(6).standard_normal(10001).astype(np.float32)
    np.testing.assert_allclose(
        r0["first"], (rng0[0] + rng1[0]) / 2.0, rtol=1e-6
    )
    assert r0["ag"] == [0.0, 1.0] and r1["ag"] == [0.0, 1.0]
    assert r0["bcast"] == [0.0, 1.0, 2.0] and r1["bcast"] == [0.0, 1.0, 2.0]
    assert r0["mismatch"] == "raised+shrunk-raised", r0["mismatch"]
    assert r1["mismatch"] == "raised+shrunk-raised", r1["mismatch"]


_COHORT_WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import numpy as np
from torchft_tpu.collectives import ReduceOp
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_from_env

marker, outdir = sys.argv[1], sys.argv[2]
gid = int(os.environ["REPLICA_GROUP_ID"])
assert init_from_env(), "cohort env missing"
c = CollectivesDeviceDist()
c.configure("", gid, int(os.environ["NUM_REPLICA_GROUPS"]))
a = np.full(64, float(gid + 1), np.float32)
c.allreduce([a], ReduceOp.AVG).wait()
if gid == 1 and not os.path.exists(marker):
    open(marker, "w").write("died")
    os._exit(1)  # first attempt: die AFTER joining the runtime
with open(os.path.join(outdir, f"g{gid}.json"), "w") as f:
    json.dump({"v": float(a[0])}, f)
"""


def test_shared_runtime_cohort_restart(tmp_path):
    """launcher --shared-runtime semantics: a worker dying after joining
    the multi-controller runtime forces a WHOLE-cohort respawn (fresh
    coordinator), and the respawned cohort completes."""
    import json

    from torchft_tpu.launcher import launch_shared_runtime

    worker = tmp_path / "worker.py"
    worker.write_text(_COHORT_WORKER.replace("__REPO__", REPO))
    marker = tmp_path / "died.marker"
    rc = launch_shared_runtime(
        [sys.executable, str(worker), str(marker), str(tmp_path)],
        num_groups=2,
        max_restarts=2,
    )
    assert rc == 0
    assert marker.exists()  # the first attempt really died
    for g in range(2):
        v = json.load(open(tmp_path / f"g{g}.json"))["v"]
        assert v == 1.5, (g, v)  # avg of 1.0 and 2.0, identical everywhere


def test_train_ddp_over_shared_runtime(tmp_path):
    """The full Manager FT loop (quorum + commit + ManagedOptimizer) with
    CollectivesDeviceDist as the data plane: 2 groups under
    launcher --shared-runtime must finish with bit-identical params."""
    import re

    from torchft_tpu.launcher import launch_shared_runtime

    wrapper = tmp_path / "wrap.sh"
    wrapper.write_text(
        "#!/bin/bash\n"
        f"cd {REPO}\n"
        f"exec {sys.executable} examples/train_ddp.py > "
        f"{tmp_path}/g${{REPLICA_GROUP_ID}}.log 2>&1\n"
    )
    wrapper.chmod(0o755)
    env_save = dict(os.environ)
    os.environ.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        DATA_PLANE="device-dist",
        STEPS="20",
    )
    try:
        rc = launch_shared_runtime([str(wrapper)], num_groups=2, max_restarts=1)
    finally:
        os.environ.clear()
        os.environ.update(env_save)
    assert rc == 0
    sums = []
    for g in range(2):
        text = (tmp_path / f"g{g}.log").read_text()
        m = re.findall(r"param_checksum=(-?\d+\.\d+)", text)
        assert m, text[-2000:]
        sums.append(m[-1])
    assert sums[0] == sums[1], sums
