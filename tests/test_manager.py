"""Manager runtime unit tests.

Ports the reference's mock-driven Manager coverage
(torchft/manager_test.py): handcrafted QuorumResults driven through
start_quorum / allreduce / should_commit with a patched ManagerClient and a
dummy data plane.
"""

from datetime import timedelta
from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from torchft_tpu.collectives import CollectivesDummy
from torchft_tpu.coordination import QuorumResult
from torchft_tpu.manager import (
    MANAGER_ADDR_KEY,
    REPLICA_ID_KEY,
    Manager,
    WorldSizeMode,
)
from torchft_tpu.store import StoreClient, StoreServer


def quorum_result(
    quorum_id=123,
    replica_rank=1,
    replica_world_size=2,
    heal=False,
    max_step=20,
    max_rank=None,
    max_world_size=2,
    recover_src_rank=None,
    recover_dst_ranks=(),
    recover_src_addresses=(),
    heal_pending=False,
):
    q = QuorumResult()
    q.quorum_id = quorum_id
    q.replica_rank = replica_rank
    q.replica_world_size = replica_world_size
    q.recover_src_manager_address = "manager address"
    q.recover_src_rank = recover_src_rank
    q.recover_dst_ranks = list(recover_dst_ranks)
    q.store_address = "store_addr/prefix"
    q.max_step = max_step
    q.max_rank = max_rank
    q.max_world_size = max_world_size
    q.heal = heal
    q.recover_src_addresses = list(recover_src_addresses)
    q.heal_pending = heal_pending or heal or bool(recover_dst_ranks)
    return q


@pytest.fixture
def store_server():
    s = StoreServer()
    yield s
    s.shutdown()


class ManagerHarness:
    def __init__(self, store_server, **kwargs):
        self.store = StoreClient(store_server.address())
        self.store.set(MANAGER_ADDR_KEY, "dummy")
        self.store.set(REPLICA_ID_KEY, "dummy_id")
        self.collectives = CollectivesDummy(rank=0, world_size=1)
        self.load_state_dict = MagicMock()
        self.transport = MagicMock()
        self.transport.metadata.return_value = "transport_meta"
        # the striped heal path prefers recv_checkpoint_multi when the
        # transport has one (a MagicMock always does) — delegate to the
        # recv_checkpoint.return_value contract the tests configure
        self.transport.recv_checkpoint_multi.side_effect = (
            lambda *a, **k: self.transport.recv_checkpoint.return_value
        )
        kwargs.setdefault("min_replica_size", 2)
        kwargs.setdefault("timeout", timedelta(seconds=10))
        # patch stays active for the harness lifetime: the healing path
        # constructs a second ManagerClient for the recovery source
        self._patcher = patch("torchft_tpu.manager.ManagerClient", autospec=True)
        self._patcher.start()
        self.manager = Manager(
            collectives=self.collectives,
            load_state_dict=self.load_state_dict,
            state_dict=lambda: {"user_key": 1},
            rank=1,
            world_size=2,
            store_addr=store_server.address(),
            checkpoint_transport=self.transport,
            **kwargs,
        )
        self.client = self.manager._client

    def shutdown(self):
        self.manager.shutdown(wait=False)
        self._patcher.stop()


@pytest.fixture
def harness(store_server):
    hs = []

    def make(**kwargs):
        h = ManagerHarness(store_server, **kwargs)
        hs.append(h)
        return h

    yield make
    for h in hs:
        h.shutdown()


def test_state_dict(harness):
    m = harness().manager
    assert m.state_dict() == {"step": 0, "batches_committed": 0}
    m.load_state_dict({"step": 1234, "batches_committed": 2345})
    assert m.current_step() == 1234
    assert m.batches_committed() == 2345


def test_user_state_dict(harness):
    h = harness()
    assert h.manager._manager_state_dict() == {
        "user": {"user_key": 1},
        "torchft": {"step": 0, "batches_committed": 0},
    }
    h.manager.set_state_dict_fns(h.load_state_dict, lambda: {"new_state": 1})
    assert h.manager._manager_state_dict()["user"] == {"new_state": 1}


def test_participation_queries_before_first_quorum(harness):
    # must not assert-crash pre-quorum (round-1 review weak #3): a trainer
    # may log participation before its first start_quorum
    m = harness().manager
    assert m.num_participants() == 0
    assert m.participating_rank() is None
    assert not m.is_participating()


def test_quorum_happy(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)

    assert m._quorum_id == -1
    assert m.current_step() == 0

    m.start_quorum()
    t = np.array([1.0, 2.0], dtype=np.float32)
    m.allreduce(t).wait()
    np.testing.assert_allclose(t, [0.5, 1.0])  # divided by num_participants=2

    h.client.should_commit.return_value = True
    assert m.should_commit()
    assert m._quorum_id == 123
    assert m.current_step() == 1
    assert m.batches_committed() == 2
    assert h.collectives.configure_count == 1
    h.transport.disallow_checkpoint.assert_called_once()

    # same quorum id -> no reconfigure
    m.start_quorum()
    assert m.should_commit()
    assert h.collectives.configure_count == 1


def test_quorum_heal_sync(harness):
    h = harness(use_async_quorum=False)
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        heal=True, max_step=20, recover_src_rank=0
    )
    h.transport.recv_checkpoint.return_value = {
        "user": {"recovered": True},
        "torchft": {"step": 20, "batches_committed": 0},
    }

    m.start_quorum()
    # sync quorum heals eagerly: state applied before returning
    assert not m._healing
    h.load_state_dict.assert_called_once_with({"recovered": True})
    assert m.current_step() == 20
    assert m.is_participating()

    h.client.should_commit.return_value = True
    assert m.should_commit()
    assert m.current_step() == 21


def test_quorum_heal_async_zeroes_contribution(harness):
    h = harness(use_async_quorum=True)
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        heal=True, max_step=20, max_rank=None, recover_src_rank=0
    )
    h.transport.recv_checkpoint.return_value = {
        "user": {"recovered": True},
        "torchft": {"step": 20, "batches_committed": 40},
    }

    m.start_quorum()
    m.wait_quorum()
    assert m._healing
    assert not m.is_participating()
    assert m.participating_rank() is None

    t = np.ones(4, dtype=np.float32)
    m.allreduce(t).wait()
    np.testing.assert_allclose(t, 0)  # healing replica contributes zeros

    h.client.should_commit.return_value = True
    assert m.should_commit()
    h.load_state_dict.assert_called_once_with({"recovered": True})
    assert m.current_step() == 21
    # batches_committed advances by participants (2) from the restored 40
    assert m.batches_committed() == 42


def test_quorum_send_checkpoint(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        max_rank=1, recover_dst_ranks=(0,), max_step=7
    )
    m.start_quorum()
    m.wait_quorum()
    h.transport.send_checkpoint.assert_called_once()
    kwargs = h.transport.send_checkpoint.call_args.kwargs
    assert kwargs["dst_ranks"] == [0]
    assert kwargs["step"] == 7
    assert kwargs["state_dict"]["user"] == {"user_key": 1}


def test_stripe_source_stages_without_assigned_healer(harness):
    # ISSUE 9: when ANYONE heals this round (heal_pending), every
    # up-to-date member stages — not just the round-robin-assigned
    # sources — so the healer can pull a stripe from each of them
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        max_rank=1, recover_dst_ranks=(), heal_pending=True, max_step=7,
        recover_src_addresses=("a0", "a1"),
    )
    m.start_quorum()
    m.wait_quorum()
    h.transport.send_checkpoint.assert_called_once()
    assert h.transport.send_checkpoint.call_args.kwargs["dst_ranks"] == []


def test_stripe_source_staging_respects_single_source_knob(harness, monkeypatch):
    monkeypatch.setenv("TORCHFT_HEAL_SOURCES", "1")
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        max_rank=1, recover_dst_ranks=(), heal_pending=True, max_step=7,
        recover_src_addresses=("a0", "a1"),
    )
    m.start_quorum()
    m.wait_quorum()
    h.transport.send_checkpoint.assert_not_called()


def test_heal_uses_multi_source_with_cohort(harness):
    # the healer resolves the whole max-step cohort (primary first) and
    # hands the transport the multi-source list + the header warmup hook
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        heal=True, max_step=20, recover_src_rank=0,
        recover_src_addresses=("manager address", "peer2 address"),
    )
    h.transport.recv_checkpoint.return_value = {
        "user": {"recovered": True},
        "torchft": {"step": 20, "batches_committed": 0},
    }
    m.start_quorum()
    m.wait_quorum()
    assert m._healing
    call = h.transport.recv_checkpoint_multi.call_args
    sources = call.args[0]
    assert len(sources) == 2  # both cohort members' metadata resolved
    assert call.kwargs["header_cb"] is not None


def test_commit_trail_recorded_at_step_boundaries(harness, monkeypatch):
    # TORCHFT_HEAL_DIFF=1: the Manager digests the committed state at
    # every start_quorum and shares the trail with the transport (the
    # differential heal's server half)
    monkeypatch.setenv("TORCHFT_HEAL_DIFF", "1")
    h = harness()
    m = h.manager
    assert m._heal_trail is not None
    assert h.transport.commit_trail is m._heal_trail
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum()
    assert m._heal_trail.steps() == [0]
    h.client.should_commit.return_value = True
    assert m.should_commit()
    m.start_quorum()
    assert m._heal_trail.steps() == [0, 1]


def test_heal_warmup_hook_fires_with_spec_tree(harness):
    import threading

    from torchft_tpu.checkpointing.serialization import flatten_state

    h = harness()
    m = h.manager
    seen = []
    done = threading.Event()

    def warmup(spec):
        seen.append(spec)
        done.set()

    m.set_heal_warmup(warmup)
    header, _ = flatten_state({"w": np.zeros((3, 2), np.float32)})
    m._heal_header_cb(header)
    assert done.wait(5.0)
    assert seen[0]["w"].shape == (3, 2)


def test_error_latching(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum()

    m.report_error(RuntimeError("boom"))
    t = np.ones(2, dtype=np.float32)
    m.allreduce(t).wait()
    np.testing.assert_allclose(t, 1.0)  # untouched no-op

    h.client.should_commit.return_value = False
    assert not m.should_commit()
    assert m.current_step() == 0

    # next quorum clears the error
    m.start_quorum()
    assert m.errored() is None


def test_allreduce_error_latches(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum()

    h.collectives.allreduce = MagicMock(side_effect=RuntimeError("net down"))
    t = np.ones(2, dtype=np.float32)
    m.allreduce(t).wait()  # completes despite the failure
    assert m.errored() is not None

    h.client.should_commit.return_value = False
    assert not m.should_commit()


def test_mixed_epoch_span_on_one_rank_vetoes_group_wide(harness):
    """Round-4 advisor low (manager.py:730): the epoch span is a LOCAL
    observation — a death-watch re-quorum can land between ops on one rank
    and entirely outside another's step. The lone observer votes False and
    client.should_commit's global conjunction aborts everyone."""
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum()
    t = np.ones(2, dtype=np.float32)
    m.allreduce(t).wait()
    # a death-watch re-quorum lands mid-step on THIS rank only
    m._quorum_id = 124
    m.allreduce(t).wait()
    assert len(m._step_epochs) == 2
    h.client.should_commit.return_value = False  # global AND result
    assert not m.should_commit()
    # this rank's local vote was the veto that fed the conjunction
    assert h.client.should_commit.call_args.args[2] is False

    # the OTHER side of the same step: a rank that saw a single epoch
    # votes True locally but is aborted by the conjunction anyway
    m.start_quorum()
    m.allreduce(t).wait()
    assert len(m._step_epochs) == 1
    h.client.should_commit.return_value = False
    assert not m.should_commit()
    assert h.client.should_commit.call_args.args[2] is True


def test_stale_death_watch_callback_dropped(harness):
    """Round-4 advisor low (manager.py:574): a POLLHUP delivered for an
    OLD plane generation must not map its ring rank through the CURRENT
    participant list (it could accuse a live replica)."""
    h = harness()
    m = h.manager
    m._death_watch_snapshot = (5, ["rep_a", "rep_b"])
    m._participant_ids = ["rep_x", "rep_y"]  # membership already replaced

    m._on_peer_death(1, plane_gen=4)  # stale generation: dropped
    assert m._evicted == set()

    m._on_peer_death(1, plane_gen=5)  # current: maps through the SNAPSHOT
    assert m._evicted == {"rep_b"}


def test_not_enough_participants(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(
        max_rank=0, max_world_size=1, replica_world_size=1
    )
    m.start_quorum()
    m.wait_quorum()
    assert m.num_participants() == 1  # < min_replica_size=2

    h.client.should_commit.return_value = False
    assert not m.should_commit()
    # local vote must have been False
    assert h.client.should_commit.call_args.args[2] is False


def test_fixed_with_spares_demotion(harness):
    h = harness(world_size_mode=WorldSizeMode.FIXED_WITH_SPARES)
    m = h.manager
    # 3 healthy replicas, min_replica_size=2 -> the third is a spare
    h.client._quorum.return_value = quorum_result(
        max_rank=2, max_world_size=3, replica_rank=2, replica_world_size=3
    )
    m.start_quorum()
    m.wait_quorum()
    assert m.num_participants() == 2
    assert m.participating_rank() is None  # demoted to spare
    t = np.ones(2, dtype=np.float32)
    m.allreduce(t).wait()
    np.testing.assert_allclose(t, 0)  # spare contributes zeros


def test_quorum_timeout_propagates(harness):
    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum(timeout=timedelta(seconds=7))
    m.wait_quorum()
    assert h.client._quorum.call_args.kwargs["timeout"] == timedelta(seconds=7)


def test_pipelined_averaging_latches_midway_error(harness):
    """Data plane dies at bucket 2 of a pipelined host-path averaging run:
    the REAL Manager must latch the error, short-circuit the remaining
    bucket ops, still hand back a structurally complete tree, and veto the
    commit (manager.py wrap_future/error-latch semantics)."""
    import jax.numpy as jnp

    from torchft_tpu.collectives import PeerGoneError, ReduceOp
    from torchft_tpu.ddp import allreduce_gradients

    h = harness()
    m = h.manager
    h.client._quorum.return_value = quorum_result(max_rank=1)
    m.start_quorum()

    calls = {"n": 0}
    real_allreduce = h.collectives.allreduce

    def flaky(arrays, op=ReduceOp.SUM):
        calls["n"] += 1
        if calls["n"] == 2:
            raise PeerGoneError(0, "peer died mid-bucket")
        return real_allreduce(arrays, op)

    h.collectives.allreduce = flaky

    grads = {f"g{i}": jnp.full((16,), float(i)) for i in range(4)}
    out = allreduce_gradients(m, grads, bucket_bytes=64)

    assert m.errored() is not None  # latched
    assert calls["n"] == 2  # buckets after the failure never hit the wire
    assert set(out) == set(grads)
    for i in range(4):
        assert np.asarray(out[f"g{i}"]).shape == (16,)

    h.client.should_commit.return_value = False
    assert m.should_commit() is False


def test_start_quorum_retries_after_timeout(harness):
    """A timed-out quorum must not poison the Manager: the next
    start_quorum is the caller's retry and starts fresh (a loaded host
    can blow one deadline without ending the training process)."""
    h = harness()
    m = h.manager

    slow = {"n": 0}

    def quorum_side_effect(**kwargs):
        slow["n"] += 1
        if slow["n"] == 1:
            raise TimeoutError("quorum deadline exceeded")
        return quorum_result(max_rank=1)

    h.client._quorum.side_effect = quorum_side_effect

    m.start_quorum()
    with pytest.raises(TimeoutError):
        m.wait_quorum()

    # retry succeeds on a fresh quorum future
    m.start_quorum()
    m.wait_quorum()
    assert m.num_participants() == 2
