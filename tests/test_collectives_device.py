"""Device-path collectives conformance tests.

Same strategy as test_collectives.py (reference process_group_test.py:67-251)
but over jax.Arrays on the virtual 8-device CPU mesh: replica groups as
threads, each owning a disjoint device set, averaging via the stacked
'ft'-axis shard_map psum. Verifies results keep each group's original
devices/sharding, SPMD desync detection, reconfiguration, and dead-peer
timeouts.
"""

from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from torchft_tpu.collectives import ReduceOp
from torchft_tpu.collectives_device import CollectivesDevice
from torchft_tpu.parallel.mesh import MeshConfig, make_mesh

EPOCH = ["e0"]


def _fresh_prefix() -> str:
    # unique epoch per test (the registry is keyed by the store prefix)
    EPOCH[0] = EPOCH[0] + "x"
    return f"store:0/torchft/{EPOCH[0]}"


def _run_world(world, fn, timeout_s=10):
    prefix = _fresh_prefix()
    colls = [CollectivesDevice(timeout=timedelta(seconds=timeout_s)) for _ in range(world)]

    def start(rank):
        colls[rank].configure(f"{prefix}/{rank}", rank, world)
        try:
            return fn(colls[rank], rank)
        finally:
            colls[rank].shutdown()

    with ThreadPoolExecutor(max_workers=world) as ex:
        return list(ex.map(start, range(world)))


class TestSingleGroup:
    def test_allreduce_identity_no_host(self):
        c = CollectivesDevice(timeout=timedelta(seconds=5))
        c.configure(f"{_fresh_prefix()}/0", 0, 1)
        a = jnp.arange(8, dtype=jnp.float32)
        out = c.allreduce([a], ReduceOp.SUM).wait()
        assert out[0] is a  # world-1 fast path: no copy, no kernel
        c.shutdown()


class TestMultiGroup:
    @pytest.mark.parametrize("world", [2, 4])
    def test_allreduce_sum_single_device_groups(self, world):
        devs = jax.devices()

        def fn(c, rank):
            a = jax.device_put(
                jnp.full((6, 3), float(rank + 1), jnp.float32), devs[rank]
            )
            out = c.allreduce([a], ReduceOp.SUM).wait()
            return out[0]

        results = _run_world(world, fn)
        want = sum(range(1, world + 1))
        for rank, r in enumerate(results):
            np.testing.assert_array_equal(np.asarray(r), want)
            assert list(r.devices()) == [devs[rank]]  # stayed on its device

    def test_allreduce_sharded_groups_keep_sharding(self):
        """Two groups × 4-device inner mesh (dp=2, tp=2): the HSDP layout."""
        devs = jax.devices()
        meshes = [
            make_mesh(MeshConfig(dp=2, tp=2), devices=devs[r * 4 : (r + 1) * 4])
            for r in range(2)
        ]
        spec = P(("dp", "fsdp"), "tp")

        def fn(c, rank):
            sharding = NamedSharding(meshes[rank], spec)
            a = jax.device_put(
                jnp.arange(32, dtype=jnp.float32).reshape(8, 4) * (rank + 1),
                sharding,
            )
            out = c.allreduce([a, a * 2], ReduceOp.SUM).wait()
            return out

        results = _run_world(2, fn)
        base = np.arange(32, dtype=np.float32).reshape(8, 4)
        for rank, (x, y) in enumerate(results):
            np.testing.assert_array_equal(np.asarray(x), base * 3)
            np.testing.assert_array_equal(np.asarray(y), base * 6)
            assert x.sharding.mesh.devices.tolist() == meshes[rank].devices.tolist()
            assert x.sharding.spec == spec

    def test_allreduce_avg_max_min(self):
        devs = jax.devices()

        def fn(c, rank):
            a = jax.device_put(jnp.full((4,), float(rank), jnp.float32), devs[rank])
            avg = c.allreduce([a], ReduceOp.AVG).wait()[0]
            mx = c.allreduce([a], ReduceOp.MAX).wait()[0]
            mn = c.allreduce([a], ReduceOp.MIN).wait()[0]
            return np.asarray(avg), np.asarray(mx), np.asarray(mn)

        for avg, mx, mn in _run_world(3, fn):
            np.testing.assert_allclose(avg, 1.0)
            np.testing.assert_array_equal(mx, 2.0)
            np.testing.assert_array_equal(mn, 0.0)

    def test_allgather_broadcast_alltoall_reduce_scatter_barrier(self):
        devs = jax.devices()
        world = 3

        def fn(c, rank):
            a = jax.device_put(jnp.full((2,), float(rank), jnp.float32), devs[rank])
            ag = c.allgather(a).wait()
            got_ag = [float(np.asarray(x)[0]) for x in ag]

            b = jax.device_put(jnp.full((2,), float(rank), jnp.float32), devs[rank])
            bc = c.broadcast(b, root=1).wait()

            ins = [
                jax.device_put(
                    jnp.full((2,), float(rank * 10 + j), jnp.float32), devs[rank]
                )
                for j in range(world)
            ]
            a2a = c.alltoall(ins).wait()
            got_a2a = [float(np.asarray(x)[0]) for x in a2a]

            rs = c.reduce_scatter(ins, ReduceOp.SUM).wait()

            c.barrier().wait()
            return got_ag, float(np.asarray(bc)[0]), got_a2a, float(np.asarray(rs)[0])

        results = _run_world(world, fn)
        for rank, (ag, bc, a2a, rs) in enumerate(results):
            assert ag == [0.0, 1.0, 2.0]
            assert bc == 1.0
            assert a2a == [j * 10 + rank for j in range(world)]
            # sum over senders j of (j*10 + rank)
            assert rs == sum(j * 10 + rank for j in range(world))

    def test_send_recv(self):
        devs = jax.devices()

        def fn(c, rank):
            if rank == 0:
                a = jax.device_put(jnp.arange(4, dtype=jnp.float32), devs[0])
                c.send(a, dst=1, tag=7).wait()
                return None
            buf = jax.device_put(jnp.zeros(4, jnp.float32), devs[1])
            got = c.recv(buf, src=0, tag=7).wait()
            return np.asarray(got)

        results = _run_world(2, fn)
        np.testing.assert_array_equal(results[1], np.arange(4, dtype=np.float32))

    def test_desync_detection(self):
        """Mismatched op kinds at the same SPMD slot fail BOTH groups fast
        (the TCP backend's frame-tag desync analogue)."""

        def fn(c, rank):
            a = jnp.ones(2)
            with pytest.raises(RuntimeError):
                c.barrier().wait(timedelta(seconds=5))
                # rank 0 issues allreduce where rank 1 issues allgather: the
                # second arriver raises synchronously, the first via its future
                if rank == 0:
                    c.allreduce([a]).wait(timedelta(seconds=5))
                else:
                    c.allgather(a).wait(timedelta(seconds=5))
            return True

        assert all(_run_world(2, fn))


class TestLifecycle:
    def test_reconfigure_new_epoch(self):
        devs = jax.devices()
        world = 2
        prefix1, prefix2 = _fresh_prefix(), _fresh_prefix()
        colls = [CollectivesDevice(timeout=timedelta(seconds=10)) for _ in range(world)]

        def run(rank):
            c = colls[rank]
            a = jax.device_put(jnp.full((2,), 1.0, jnp.float32), devs[rank])
            c.configure(f"{prefix1}/{rank}", rank, world)
            r1 = np.asarray(c.allreduce([a]).wait()[0])
            c.configure(f"{prefix2}/{rank}", rank, world)
            r2 = np.asarray(c.allreduce([a]).wait()[0])
            c.shutdown()
            return r1, r2

        with ThreadPoolExecutor(max_workers=world) as ex:
            for r1, r2 in ex.map(run, range(world)):
                np.testing.assert_array_equal(r1, 2.0)
                np.testing.assert_array_equal(r2, 2.0)

    def test_dead_peer_times_out(self):
        """A group that never shows up fails the op within the deadline,
        not forever (the TCP backend's silent-peer analogue)."""
        prefix = _fresh_prefix()
        c0 = CollectivesDevice(timeout=timedelta(seconds=1))
        c1 = CollectivesDevice(timeout=timedelta(seconds=30))

        def join(c, rank):
            c.configure(f"{prefix}/{rank}", rank, 2)

        with ThreadPoolExecutor(max_workers=2) as ex:
            list(ex.map(lambda args: join(*args), [(c0, 0), (c1, 1)]))

        # rank 1 never calls allreduce
        work = c0.allreduce([jnp.ones(2)])
        with pytest.raises(TimeoutError):
            work.wait(timedelta(seconds=5))
        c0.shutdown()
        c1.shutdown()

    def test_reconfigure_fails_pending_ops(self):
        """A member leaving (reconfigure) resolves the other members'
        in-flight futures with an error instead of stranding them."""
        prefix = _fresh_prefix()
        c0 = CollectivesDevice(timeout=timedelta(seconds=30))
        c1 = CollectivesDevice(timeout=timedelta(seconds=30))

        def join(c, rank):
            c.configure(f"{prefix}/{rank}", rank, 2)

        with ThreadPoolExecutor(max_workers=2) as ex:
            list(ex.map(lambda args: join(*args), [(c0, 0), (c1, 1)]))

        work = c0.allreduce([jnp.ones(2)])
        c1.shutdown()  # leaves the epoch
        with pytest.raises(RuntimeError, match="reconfigured"):
            work.wait(timedelta(seconds=5))
        c0.shutdown()

    def test_incongruent_shardings_error(self):
        devs = jax.devices()

        def fn(c, rank):
            shape = (4, 4) if rank == 0 else (2, 8)
            a = jax.device_put(jnp.ones(shape, jnp.float32), devs[rank])
            with pytest.raises(RuntimeError, match="congruent"):
                c.allreduce([a]).wait(timedelta(seconds=5))
            return True

        assert all(_run_world(2, fn))
