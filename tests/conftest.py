import os

# Force JAX onto a virtual 8-device CPU mesh for all tests: multi-chip
# sharding is validated without TPU hardware (the driver separately
# dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache, shared with every worker subprocess
# the soak tests spawn (they inherit the env): the chaos/recovery tiers
# respawn the same toy models dozens of times and each respawn otherwise
# recompiles from scratch — on the 2-core CI box that recompile tax alone
# pushes the full 'not slow' tier against its wall-clock budget.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/tft_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize registers the TPU PJRT plugin and can win
# over the env var; pin the platform explicitly too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Polyfill the modern jax API surface (jax.shard_map / jax.set_mesh /
# jax.sharding.get_abstract_mesh) onto older runtimes; tests use the
# modern spellings directly.
import torchft_tpu.utils.jax_compat  # noqa: E402,F401

# Let in-process tests exercise the kill RPC without nuking pytest.
os.environ.setdefault("TORCHFT_TPU_SOFT_KILL", "1")

# Subprocess timeout scaling: caps tuned on a multi-core box flake on a
# 1-core one under contention (round-3 review weak #5 — a 240s example
# run hit TimeoutExpired while a bench ran). Scale by core count so red
# means bug, not busy box.
_CPUS = os.cpu_count() or 1
SUBPROC_TIMEOUT_SCALE = 1 if _CPUS >= 4 else (2 if _CPUS >= 2 else 4)


def scaled_timeout(seconds: float) -> float:
    return seconds * SUBPROC_TIMEOUT_SCALE


# The environmental-corruption catalog (ROADMAP open item, PR 2
# post-mortem) lives in torchft_tpu/faultinject/core.py so the scenario
# runner and this test tier recognize the same signatures; multi-process
# soaks skip — not fail — on them, so red means NEW bug, not the
# documented one. Imported lazily: conftest must not pull the package
# (and its native auto-build) in before the env fixtures run.


def known_corruption_signature(text: str):
    """Return the matched known-corruption signature in ``text``, or None."""
    from torchft_tpu.faultinject.core import ENV_CORRUPTION_SIGNATURES

    for sig in ENV_CORRUPTION_SIGNATURES:
        if sig in text:
            return sig
    return None


def injected_kill_evidence(evidence_dir=None):
    """Fired kill/torn records from the fault-injection plane's evidence
    files (``TORCHFT_FAULT_EVIDENCE_DIR``). A worker that died because a
    SCHEDULED injection killed it writes this record before dying — both
    the Python engine (faultinject/core.py) and the native plane
    (native/faultinject.h) use the same directory and JSONL shape."""
    from torchft_tpu.faultinject.core import read_evidence

    return [
        r
        for r in read_evidence(evidence_dir)
        if r.get("action") in ("kill", "torn", "drop")
    ]


def skip_if_known_corruption(
    text: str, rcs=(), nan_checksums: bool = False, evidence_dir=None
):
    """One policy for every multi-process soak: ``pytest.skip`` when a
    failure carries the documented pre-existing corruption evidence — a
    known signature in ``text``, a signal-class return code in ``rcs``,
    or (opt-in) the all-nan-checksum divergence form. Returns normally
    when the failure looks like a NEW bug, so the caller re-raises.

    Injection evidence WINS over a signature match: a worker killed by a
    scheduled fault-injection (SIGKILL shows up as rc -9/-6-class noise
    and can segfault jit mid-step, mimicking the environmental signature)
    must never be laundered into a skip — the test scheduled that death
    and must handle or fail it explicitly."""
    import pytest

    from torchft_tpu.faultinject.core import CORRUPTION_SIGNAL_RCS

    if injected_kill_evidence(evidence_dir):
        return

    sig = known_corruption_signature(text)
    if sig is None and any(rc in CORRUPTION_SIGNAL_RCS for rc in rcs):
        sig = f"signal rc in {sorted(set(rcs))}"
    if sig is None and nan_checksums and "param_checksum=nan" in text:
        # the divergence mode of the same corruption: no crash, but the
        # data plane silently poisoned the averages on every worker
        sig = "param_checksum=nan"
    if sig is not None:
        # Triaged artifact instead of a bare skip (ISSUE 10): when the
        # soak ran with black boxes armed, reconstruct the incident and
        # record the postmortem classification next to the evidence —
        # an environmental-churn skip then leaves a timeline naming the
        # victim and its in-flight op, not just a signature string.
        pm = ""
        try:
            import json

            bb_dir = os.environ.get("TORCHFT_BLACKBOX_DIR") or evidence_dir
            if bb_dir and os.path.isdir(bb_dir):
                from torchft_tpu.telemetry import postmortem

                report = postmortem.analyze(bb_dir, log_text=text)
                out_dir = evidence_dir or bb_dir
                out = os.path.join(out_dir, "postmortem_skip.json")
                with open(out, "w", encoding="utf-8") as f:
                    json.dump(report, f, indent=1, default=str)
                pm = f"; postmortem={report['classification']} -> {out}"
        except Exception:  # noqa: BLE001 — forensics must not fail the skip
            pm = ""
        pytest.skip(
            f"known pre-existing native corruption in a worker ({sig!r})"
            f"{pm}; see ROADMAP open items"
        )
