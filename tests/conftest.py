import os

# Force JAX onto a virtual 8-device CPU mesh for all tests: multi-chip
# sharding is validated without TPU hardware (the driver separately
# dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache, shared with every worker subprocess
# the soak tests spawn (they inherit the env): the chaos/recovery tiers
# respawn the same toy models dozens of times and each respawn otherwise
# recompiles from scratch — on the 2-core CI box that recompile tax alone
# pushes the full 'not slow' tier against its wall-clock budget.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/tft_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize registers the TPU PJRT plugin and can win
# over the env var; pin the platform explicitly too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Polyfill the modern jax API surface (jax.shard_map / jax.set_mesh /
# jax.sharding.get_abstract_mesh) onto older runtimes; tests use the
# modern spellings directly.
import torchft_tpu.utils.jax_compat  # noqa: E402,F401

# Let in-process tests exercise the kill RPC without nuking pytest.
os.environ.setdefault("TORCHFT_TPU_SOFT_KILL", "1")

# Subprocess timeout scaling: caps tuned on a multi-core box flake on a
# 1-core one under contention (round-3 review weak #5 — a 240s example
# run hit TimeoutExpired while a bench ran). Scale by core count so red
# means bug, not busy box.
_CPUS = os.cpu_count() or 1
SUBPROC_TIMEOUT_SCALE = 1 if _CPUS >= 4 else (2 if _CPUS >= 2 else 4)


def scaled_timeout(seconds: float) -> float:
    return seconds * SUBPROC_TIMEOUT_SCALE
