"""Parallelism tests on the virtual 8-device CPU mesh.

Covers what the reference cannot (SURVEY.md §2.3): tensor/sequence/
pipeline/expert parallel shardings of the flagship transformer, ring
attention numerics vs plain attention, and pipeline vs sequential
equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    param_specs,
)
from torchft_tpu.ops.attention import attention, ring_attention
from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
from torchft_tpu.parallel.train_step import TrainStep

CFG = dict(
    vocab_size=128,
    d_model=32,
    n_layers=4,
    n_heads=4,
    head_dim=8,
    d_ff=64,
    dtype=jnp.float32,  # CPU test: keep numerics comparable
)


def tokens(b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG["vocab_size"], (b, s)), jnp.int32)


class TestRingAttention:
    def test_matches_plain(self):
        mesh = make_mesh(MeshConfig(sp=4, tp=2))
        rng = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(r, (2, 16, 4, 8), jnp.float32)
            for r in jax.random.split(rng, 3)
        )
        expect = attention(q, k, v, causal=True)
        with jax.set_mesh(mesh):
            got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-5)

    def test_grads_match(self):
        mesh = make_mesh(MeshConfig(sp=4))
        rng = jax.random.PRNGKey(1)
        q, k, v = (
            jax.random.normal(r, (1, 8, 2, 4), jnp.float32)
            for r in jax.random.split(rng, 3)
        )

        def loss_plain(q):
            return attention(q, k, v).sum()

        def loss_ring(q):
            return ring_attention(q, k, v, mesh).sum()

        g1 = jax.grad(loss_plain)(q)
        with jax.set_mesh(mesh):
            g2 = jax.jit(jax.grad(loss_ring))(q)
        np.testing.assert_allclose(np.asarray(g2), np.asarray(g1), atol=2e-5)


class TestRematPolicy:
    def test_dots_matches_all_and_typo_raises(self):
        """remat_policy='dots' (save matmul outputs) must be numerically
        identical to full-layer recompute, and unknown values must raise
        instead of silently paying full recompute (round-5 review)."""
        mesh = make_mesh(MeshConfig())
        t = tokens()
        losses, grads = [], []
        for policy in ("all", "dots"):
            cfg = TransformerConfig(**{**CFG, "remat_policy": policy})
            params = init_params(jax.random.PRNGKey(0), cfg)
            with jax.set_mesh(mesh):
                l, g = jax.jit(
                    jax.value_and_grad(lambda p, t: loss_fn(p, t, cfg, mesh))
                )(params, t)
            losses.append(float(l))
            grads.append(g)
        assert losses[0] == pytest.approx(losses[1], rel=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            grads[0],
            grads[1],
        )

        bad = TransformerConfig(**{**CFG, "remat_policy": "dot"})
        params = init_params(jax.random.PRNGKey(0), bad)
        with pytest.raises(ValueError, match="remat_policy"):
            with jax.set_mesh(mesh):
                jax.jit(lambda p, t: loss_fn(p, t, bad, mesh))(params, t)


class TestTransformer:
    def test_dense_loss_and_grads(self):
        cfg = TransformerConfig(**CFG)
        mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
        params = init_params(jax.random.PRNGKey(0), cfg)
        with jax.set_mesh(mesh):
            loss = jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(params, tokens())
        assert np.isfinite(float(loss))
        assert float(loss) < 2 * np.log(CFG["vocab_size"])

    def test_pipeline_matches_sequential(self):
        base = TransformerConfig(**CFG)
        piped = TransformerConfig(**{**CFG, "pp": 2, "microbatches": 2})
        mesh1 = make_mesh(MeshConfig())
        mesh2 = make_mesh(MeshConfig(pp=2))

        p1 = init_params(jax.random.PRNGKey(0), base)
        # same weights reshaped into [2, L/2] stages
        p2 = jax.tree_util.tree_map(
            lambda a: a.reshape(2, a.shape[1] // 2, *a.shape[2:])
            if a.ndim >= 2 and a.shape[0] == 1
            else a,
            p1,
        )
        t = tokens()
        with jax.set_mesh(mesh1):
            l1 = jax.jit(lambda p, t: loss_fn(p, t, base, mesh1))(p1, t)
        with jax.set_mesh(mesh2):
            l2 = jax.jit(lambda p, t: loss_fn(p, t, piped, mesh2))(p2, t)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_moe_expert_parallel(self):
        cfg = TransformerConfig(**{**CFG, "n_experts": 4})
        mesh = make_mesh(MeshConfig(ep=4, tp=2))
        params = init_params(jax.random.PRNGKey(0), cfg)
        with jax.set_mesh(mesh):
            loss = jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(params, tokens())
        assert np.isfinite(float(loss))


class TestTrainStep:
    def test_fused_step_learns(self):
        cfg = TransformerConfig(**CFG)
        mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
        ts = TrainStep(cfg, optax.adam(1e-2), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        t = ts.shard_batch(tokens())
        losses = []
        for _ in range(5):
            loss, params, opt_state = ts.step(params, opt_state, t)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_split_grads_apply(self):
        cfg = TransformerConfig(**CFG)
        mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
        ts = TrainStep(cfg, optax.sgd(1e-2), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        t = ts.shard_batch(tokens())
        loss0, grads = ts.grads(params, t)
        # host round-trip (the FT cross-group path)
        host_grads = jax.tree_util.tree_map(np.asarray, grads)
        params, opt_state = ts.apply(params, opt_state, host_grads)
        loss1, _ = ts.grads(params, t)
        assert float(loss1) < float(loss0)

    def test_full_5d_mesh(self):
        """dp x pp x sp x tp all >1 in one step (the dryrun shape)."""
        cfg = TransformerConfig(**{**CFG, "pp": 2, "microbatches": 2})
        mesh = make_mesh(MeshConfig(pp=2, sp=2, tp=2))
        ts = TrainStep(cfg, optax.adam(1e-2), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        t = ts.shard_batch(tokens())
        loss, params, opt_state = ts.step(params, opt_state, t)
        assert np.isfinite(float(loss))


class TestNoInvoluntaryRemat:
    """Round-4 regression guard (round-3 review missing #2): the sharded
    step must compile without XLA's "[SPMD] Involuntary full
    rematerialization" fallback — it silently replicates a full tensor
    (the embed table, historically) on every device every step. capfd
    sees the C++ absl warning on fd 2."""

    def _run(self, cfg_over, mesh_over):
        cfg = TransformerConfig(**{**CFG, **cfg_over})
        mesh = make_mesh(MeshConfig(**mesh_over))
        ts = TrainStep(cfg, optax.adam(1e-2), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        t = ts.shard_batch(tokens())
        loss, _, _ = ts.step(params, opt_state, t)
        assert np.isfinite(float(loss))

    def test_fsdp_pp_sp_step_has_no_remat_fallback(self, capfd):
        self._run(
            {"pp": 2, "microbatches": 2}, dict(fsdp=2, pp=2, sp=2)
        )
        assert "Involuntary full rematerialization" not in capfd.readouterr().err

    def test_ep_tp_fsdp_moe_step_has_no_remat_fallback(self, capfd):
        self._run({"n_experts": 4}, dict(ep=2, tp=2, fsdp=2))
        assert "Involuntary full rematerialization" not in capfd.readouterr().err
