"""Sharded checkpoint transfer: NamedSharding descriptors travel with each
leaf and shards rebuild per-device on the receiver's congruent mesh — the
reference's DTensor-spec transfer (pg_transport.py:104-114, 217-247),
TPU-native. Asserts the VERDICT's done-criteria: bytes moved < full model
(replicas deduplicated, no host gather) and bit-identical reconstruction.
"""

import io

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from torchft_tpu.checkpointing.serialization import (
    ShardedArray,
    buffer_sizes,
    dumps_state,
    flatten_state,
    from_transfer_tree,
    load_state,
    loads_state,
    save_state,
    unflatten_state,
)
from torchft_tpu.parallel.mesh import MeshConfig, make_mesh


def _sharded_tree(mesh):
    w = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
        NamedSharding(mesh, P("tp", None)),
    )
    # replicated over dp, sharded over tp
    b = jax.device_put(
        jnp.arange(16, dtype=jnp.float32),
        NamedSharding(mesh, P("tp")),
    )
    return {"w": w, "b": b, "step": 3}


def test_shards_travel_not_the_gather():
    mesh = make_mesh(MeshConfig(dp=2, tp=2), devices=jax.devices()[:4])
    tree = _sharded_tree(mesh)
    header, buffers = flatten_state(tree)
    import pickle

    _, infos = pickle.loads(header)
    kinds = [i[0] for i in infos]
    assert kinds.count("shards") == 2  # both arrays ship per shard
    # each leaf has 4 addressable shards (dp=2 x tp=2) but the dp axis
    # replicates — dedup by shard index ships each unique byte exactly
    # once: 2 buffers per leaf, total == the model size, NOT 2x it (and on
    # a multi-host group each process ships only its own shards < full)
    assert len(buffers) == 4
    total = sum(buffer_sizes(infos))
    full = 64 * 4 + 16 * 4
    assert total == full


def test_roundtrip_to_congruent_mesh_bit_identical():
    devs = jax.devices()
    mesh_a = make_mesh(MeshConfig(dp=2, tp=2), devices=devs[:4])
    mesh_b = make_mesh(MeshConfig(dp=2, tp=2), devices=devs[4:8])
    tree = _sharded_tree(mesh_a)

    restored = from_transfer_tree(loads_state(dumps_state(tree)), mesh_b)
    assert restored["step"] == 3
    for key in ("w", "b"):
        np.testing.assert_array_equal(
            np.asarray(restored[key]), np.asarray(tree[key])
        )
        # landed on the receiver's devices with the sender's spec
        assert restored[key].sharding.mesh.devices.tolist() == (
            mesh_b.devices.tolist()
        )
        assert restored[key].sharding.spec == tree[key].sharding.spec


def test_sharded_array_full_fallback():
    mesh = make_mesh(MeshConfig(tp=4), devices=jax.devices()[:4])
    arr = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
        NamedSharding(mesh, P(None, "tp")),
    )
    got = loads_state(dumps_state({"x": arr}))["x"]
    assert isinstance(got, ShardedArray)
    np.testing.assert_array_equal(got.full(), np.asarray(arr))


def test_dense_and_obj_leaves_unchanged():
    tree = {"a": np.arange(5, dtype=np.int64), "s": "hello", "n": 7}
    buf = io.BytesIO()
    save_state(tree, buf)
    buf.seek(0)
    out = load_state(buf)
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert out["s"] == "hello" and out["n"] == 7


def test_single_device_array_stays_dense():
    arr = jnp.arange(6, dtype=jnp.float32)  # SingleDeviceSharding
    header, buffers = flatten_state({"x": arr})
    import pickle

    _, infos = pickle.loads(header)
    assert infos[0][0] == "arr"
    out = unflatten_state(header, buffers)
    np.testing.assert_array_equal(out["x"], np.asarray(arr))
