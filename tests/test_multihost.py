"""Multi-host replica groups: the inner mesh spans 2 processes per group
(multi-controller JAX over CPU), the elastic cross-group axis rides
per-rank CollectivesTcp — the torchrun-per-group analogue
(/root/reference/torchft/torchx.py:11-76) with jax.distributed instead of
torch.distributed. Two groups x two processes, full FT loop, asserting
cross-group state convergence (the BASELINE.md v5e-32 north-star shape:
replica groups that span hosts)."""

import os
import subprocess
import sys

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.launcher import _free_port
from torchft_tpu.store import StoreServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_groups_of_two_processes(tmp_path):
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    lh_addr = lighthouse.address()
    stores = [StoreServer(), StoreServer()]
    procs = []
    outs = [str(tmp_path / f"g{g}.out") for g in range(2)]
    try:
        for g in range(2):
            coordinator = f"localhost:{_free_port()}"
            for rank in range(2):
                env = dict(os.environ)
                env.pop("XLA_FLAGS", None)  # worker pins its own device count
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            os.path.join(REPO, "tests", "mh_worker.py"),
                            str(g),
                            str(rank),
                            "2",
                            coordinator,
                            stores[g].address(),
                            lh_addr,
                            outs[g],
                        ],
                        env=env,
                        cwd=REPO,
                    )
                )
        for p in procs:
            assert p.wait(timeout=180) == 0
        results = []
        for out in outs:
            with open(out) as f:
                step, checksum = f.read().split()
                results.append((step, checksum))
        assert results[0][0] == "3" and results[1][0] == "3"
        # cross-group gradient averaging kept the two groups' sharded
        # params bit-identical (checksums computed on each group's mesh)
        assert results[0][1] == results[1][1], results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in stores:
            s.shutdown()
        lighthouse.shutdown()
