"""Multi-host replica groups: the inner mesh spans 2 processes per group
(multi-controller JAX over CPU), the elastic cross-group axis rides
per-rank CollectivesTcp — the torchrun-per-group analogue
(/root/reference/torchft/torchx.py:11-76) with jax.distributed instead of
torch.distributed. Two groups x two processes, full FT loop, asserting
cross-group state convergence (the BASELINE.md v5e-32 north-star shape:
replica groups that span hosts)."""

import os
import re
import subprocess
import sys

import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.launcher import _free_port
from torchft_tpu.store import StoreServer

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
from conftest import scaled_timeout, skip_if_known_corruption

pytestmark = pytest.mark.soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _KillRespawnSkip(Exception):
    """Run finished before the kill could land mid-flight."""


def _kill_respawn_attempt(workdir) -> None:
    """One kill/respawn scenario run; raises AssertionError/TimeoutError
    on failure, _KillRespawnSkip when the run outpaced the kill."""
    import signal
    import time

    workdir.mkdir(exist_ok=True)
    wrapper = workdir / "wrap.sh"
    wrapper.write_text(
        "#!/bin/bash\n"
        f"cd {REPO}\n"
        "exec python examples/train_hsdp.py >> "
        f"{workdir}/g${{REPLICA_GROUP_ID}}_r${{RANK}}.$$.log 2>&1\n"
    )
    wrapper.chmod(0o755)
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        STEPS="12",
        FSDP="2",
        TP="2",
        BATCH="8",
        SEQ="16",
        # any wedged worker self-captures its flight dump next to the logs
        TORCHFT_FLIGHT_DIR=str(workdir),
    )
    launcher = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "torchft_tpu.launcher",
            "--groups",
            "2",
            "--nproc",
            "2",
            "--",
            str(wrapper),
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # wait for group 1 to reach step 4, then SIGKILL that exact worker
        # (its pid is embedded in the log filename — no pkill guessing).
        # Deliberately NOT scaled: tier-1's whole-suite wall-clock budget
        # can't absorb a scaled worst case here, and a healthy run reaches
        # step 4 well inside the raw budget even with a respawn or two.
        deadline = time.monotonic() + 240
        victim = None
        while time.monotonic() < deadline:
            for p in workdir.glob("g1_r0.*.log"):
                if "step=4 " in p.read_text():
                    victim = p
                    break
            if victim is not None:
                break
            assert launcher.poll() is None, "launcher died early"
            time.sleep(0.5)
        else:
            raise TimeoutError("group 1 never reached step 4")
        if "done:" in victim.read_text():
            raise _KillRespawnSkip()
        pid = int(victim.name.split(".")[1])
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            # the worker died organically between the log scan and the
            # kill (this box's churn — see post-mortem below): the group
            # is already down and the launcher is respawning it, which is
            # exactly the scenario under test
            pass
        assert launcher.wait(timeout=300) == 0
    finally:
        if launcher.poll() is None:
            launcher.send_signal(signal.SIGINT)
            try:
                launcher.wait(timeout=30)
            except subprocess.TimeoutExpired:
                launcher.kill()
                launcher.wait(timeout=30)

    sums = []
    healed = 0
    for p in sorted(workdir.glob("g*_r*.log")):
        text = p.read_text()
        healed += text.count("healing: fetching checkpoint metadata")
        m = re.findall(r"param_checksum=(-?\d+\.\d+)", text)
        if m:
            sums.append(m[-1])
    assert len(sums) == 4, sums  # both original g0 procs + respawned g1 pair
    assert len(set(sums)) == 1, sums  # bit-identical across hosts and groups
    assert healed >= 1  # the respawned group actually live-healed


def test_multihost_group_kill_respawn_heal(tmp_path):
    """The north-star scenario (BASELINE.md): replica groups spanning
    processes, one group SIGKILLed mid-run. The launcher tears down and
    respawns the whole group (fresh store + fresh jax coordinator — a
    multi-controller runtime cannot lose a member and live, so groups
    fail as units, exactly like torchrun+torchelastic in the reference);
    the respawned pair re-forms its mesh, rejoins the quorum, and heals
    its SHARDED state per rank from the survivor. All four processes must
    end bit-identical.

    Flake post-mortem (PR 2, recorder evidence). A recorded failing run
    showed the STEP-0-HEALED group dying organically at step 3 inside the
    jitted value_and_grad dispatch (``RuntimeError: Too few elements for
    TreeDef node``) ~1 s after committing step 2; the survivor detected
    the death instantly (death-watch eviction at +0.7 s) but then timed
    out its 60 s quorum long-poll waiting for the respawn — one organic
    post-heal crash cascading into this test's startup-timeout mode. The
    leading hypothesis is post-heal dispatch churn: the healed replica's
    opt_state comes back as uncommitted host leaves, so its first apply
    retraces with different input types than the survivors. Re-committing
    those leaves onto the live tree's shardings is NOT a valid fix — in a
    multi-controller group device_put resolves jit-output scalar
    shardings to one local device and apply then rejects the global/local
    device mix (verified experimentally). A/B runs on an UNMODIFIED
    checkout reproduced the crash (and under load the same point shows
    glibc heap-corruption aborts), so this is a pre-existing
    native/runtime corruption — tracked as a ROADMAP open item. The
    deflake: one bounded attempt, and when the failure's worker logs
    carry a KNOWN corruption signature the test SKIPS instead of failing
    (red must mean a NEW bug); flight dumps + the merged lighthouse
    /trace self-capture every recurrence for the follow-up PR."""
    workdir = tmp_path / "attempt0"
    try:
        _kill_respawn_attempt(workdir)
    except _KillRespawnSkip:
        pytest.skip("run finished before the kill could land mid-flight")
    except (AssertionError, TimeoutError):
        text = "".join(
            p.read_text() for p in workdir.glob("g*_r*.log")
        )
        # shared skip policy; nan_checksums opts into the divergence mode
        # (no crash, every surviving worker converged on a nan checksum)
        skip_if_known_corruption(text, nan_checksums=True)
        raise


def test_two_groups_of_two_processes(tmp_path):
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    lh_addr = lighthouse.address()
    stores = [StoreServer(), StoreServer()]
    procs = []
    errs = []
    outs = [str(tmp_path / f"g{g}.out") for g in range(2)]
    try:
        for g in range(2):
            coordinator = f"localhost:{_free_port()}"
            for rank in range(2):
                env = dict(os.environ)
                env.pop("XLA_FLAGS", None)  # worker pins its own device count
                err_path = tmp_path / f"g{g}_r{rank}.stderr"
                errs.append(err_path)
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            os.path.join(REPO, "tests", "mh_worker.py"),
                            str(g),
                            str(rank),
                            "2",
                            coordinator,
                            stores[g].address(),
                            lh_addr,
                            outs[g],
                        ],
                        env=env,
                        cwd=REPO,
                        stderr=open(err_path, "wb"),
                    )
                )
        rcs = [p.wait(timeout=scaled_timeout(180)) for p in procs]
        if any(rc != 0 for rc in rcs):
            text = "".join(
                e.read_text(errors="replace") for e in errs if e.exists()
            )
            skip_if_known_corruption(text, rcs=rcs)
            assert False, (
                f"worker exited nonzero (rcs={rcs}); "
                f"stderr tail: {text[-3000:]}"
            )
        results = []
        for out in outs:
            with open(out) as f:
                step, checksum = f.read().split()
                results.append((step, checksum))
        assert results[0][0] == "3" and results[1][0] == "3"
        # cross-group gradient averaging kept the two groups' sharded
        # params bit-identical (checksums computed on each group's mesh)
        assert results[0][1] == results[1][1], results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in stores:
            s.shutdown()
        lighthouse.shutdown()
