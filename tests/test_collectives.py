"""Collectives conformance tests.

Mirrors the reference's PG test strategy (process_group_test.py:67-251):
every collective exercised on world-size-1, then multi-rank semantics checks
with rank threads sharing one store, then reconfiguration.
"""

from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.collectives import (
    CollectivesDummy,
    CollectivesTcp,
    ErrorSwallowingCollectives,
    ReduceOp,
)
from torchft_tpu.store import StoreServer


@pytest.fixture()
def store():
    s = StoreServer()
    yield s
    s.shutdown()


def _run_world(store, world, fn, prefix="test", **coll_kwargs):
    """Run fn(coll, rank) on `world` configured TCP collectives, one thread
    per rank (the reference's in-process multi-rank harness). Extra kwargs
    go to the CollectivesTcp constructors (e.g. wire_dtype)."""
    coll_kwargs.setdefault("timeout", timedelta(seconds=10))
    colls = [
        CollectivesTcp(hostname="localhost", **coll_kwargs)
        for _ in range(world)
    ]

    def start(rank):
        colls[rank].configure(f"{store.address()}/{prefix}", rank, world)
        try:
            return fn(colls[rank], rank)
        finally:
            colls[rank].shutdown()

    with ThreadPoolExecutor(max_workers=world) as ex:
        return list(ex.map(start, range(world)))


class TestSingleRank:
    def test_all_ops(self, store):
        c = CollectivesTcp(timeout=timedelta(seconds=5), hostname="localhost")
        c.configure(f"{store.address()}/solo", 0, 1)
        a = np.arange(8, dtype=np.float32)

        out = c.allreduce([a.copy()], ReduceOp.SUM).wait()
        np.testing.assert_array_equal(out[0], a)

        ag = c.allgather(a).wait()
        assert len(ag) == 1
        np.testing.assert_array_equal(ag[0], a)

        b = a.copy()
        c.broadcast(b, root=0).wait()
        np.testing.assert_array_equal(b, a)

        rs = c.reduce_scatter([a.copy()], ReduceOp.SUM).wait()
        np.testing.assert_array_equal(rs, a)

        a2a = c.alltoall([a.copy()]).wait()
        np.testing.assert_array_equal(a2a[0], a)

        c.barrier().wait()
        assert c.size() == 1 and c.rank() == 0
        c.shutdown()


class TestMultiRank:
    @pytest.mark.parametrize("world", [2, 3, 4])
    def test_allreduce_sum(self, store, world):
        def fn(c, rank):
            a = np.full(13, float(rank + 1), dtype=np.float32)
            return c.allreduce([a], ReduceOp.SUM).wait(timedelta(seconds=10))[0]

        outs = _run_world(store, world, fn, prefix=f"ar{world}")
        want = sum(range(1, world + 1))
        for out in outs:
            np.testing.assert_allclose(out, want)

    def test_allreduce_bfloat16_ring(self, store):
        """ml_dtypes buffers must cross the ring (gradients are bf16; plain
        memoryview() rejects them — _bytes_view reinterprets as uint8)."""
        import ml_dtypes

        def fn(c, rank):
            a = np.full(300, float(rank + 1), dtype=ml_dtypes.bfloat16)
            return c.allreduce([a], ReduceOp.AVG).wait(timedelta(seconds=10))[0]

        outs = _run_world(store, 2, fn, prefix="arbf16")
        for out in outs:
            assert out.dtype == ml_dtypes.bfloat16
            np.testing.assert_allclose(out.astype(np.float32), 1.5)

    def test_allreduce_avg_and_max(self, store):
        def fn(c, rank):
            a = np.full(5, float(rank), dtype=np.float64)
            avg = c.allreduce([a.copy()], ReduceOp.AVG).wait()[0]
            mx = c.allreduce([a.copy()], ReduceOp.MAX).wait()[0]
            return avg, mx

        outs = _run_world(store, 3, fn, prefix="avgmax")
        for avg, mx in outs:
            np.testing.assert_allclose(avg, 1.0)  # (0+1+2)/3
            np.testing.assert_allclose(mx, 2.0)

    def test_allreduce_multiple_arrays_and_dtypes(self, store):
        def fn(c, rank):
            xs = [
                np.full(3, rank + 1, dtype=np.float32),
                np.full((2, 2), rank + 1, dtype=np.int64),
            ]
            return c.allreduce(xs, ReduceOp.SUM).wait()

        outs = _run_world(store, 2, fn, prefix="multi")
        for xs in outs:
            np.testing.assert_allclose(xs[0], 3.0)
            np.testing.assert_array_equal(xs[1], np.full((2, 2), 3))

    def test_allgather(self, store):
        def fn(c, rank):
            return c.allgather(np.full(4, rank, dtype=np.float32)).wait()

        outs = _run_world(store, 3, fn, prefix="ag")
        for got in outs:
            for r in range(3):
                np.testing.assert_allclose(got[r], float(r))

    def test_broadcast(self, store):
        def fn(c, rank):
            a = (
                np.arange(6, dtype=np.float32)
                if rank == 1
                else np.zeros(6, dtype=np.float32)
            )
            c.broadcast(a, root=1).wait()
            return a

        outs = _run_world(store, 3, fn, prefix="bc")
        for a in outs:
            np.testing.assert_allclose(a, np.arange(6, dtype=np.float32))

    def test_reduce_scatter(self, store):
        world = 3

        def fn(c, rank):
            # arrays[j] is this rank's contribution to rank j
            arrays = [
                np.full(4, (rank + 1) * 10 + j, dtype=np.float32)
                for j in range(world)
            ]
            return c.reduce_scatter(arrays, ReduceOp.SUM).wait()

        outs = _run_world(store, world, fn, prefix="rs")
        for j, got in enumerate(outs):
            want = sum((r + 1) * 10 + j for r in range(world))
            np.testing.assert_allclose(got, float(want))

    def test_alltoall(self, store):
        world = 3

        def fn(c, rank):
            arrays = [
                np.full(2, rank * 10 + j, dtype=np.int32) for j in range(world)
            ]
            return c.alltoall(arrays).wait()

        outs = _run_world(store, world, fn, prefix="a2a")
        for j, got in enumerate(outs):
            for r in range(world):
                np.testing.assert_array_equal(got[r], r * 10 + j)

    def test_send_recv(self, store):
        def fn(c, rank):
            if rank == 0:
                c.send(np.arange(5, dtype=np.float32), dst=1, tag=7).wait()
                return None
            buf = np.zeros(5, dtype=np.float32)
            c.recv(buf, src=0, tag=7).wait()
            return buf

        outs = _run_world(store, 2, fn, prefix="p2p")
        np.testing.assert_allclose(outs[1], np.arange(5, dtype=np.float32))

    def test_barrier(self, store):
        def fn(c, rank):
            c.barrier().wait(timedelta(seconds=10))
            return True

        assert all(_run_world(store, 3, fn, prefix="bar"))

    def test_large_uneven_allreduce(self, store):
        # array smaller than world and a large one exercising chunking
        def fn(c, rank):
            small = np.full(2, float(rank), dtype=np.float32)
            big = np.full(100003, float(rank + 1), dtype=np.float32)
            return c.allreduce([small, big], ReduceOp.SUM).wait(
                timedelta(seconds=30)
            )

        outs = _run_world(store, 4, fn, prefix="big")
        for small, big in outs:
            np.testing.assert_allclose(small, 6.0)
            np.testing.assert_allclose(big, 10.0)

    def test_reconfigure_changes_world(self, store):
        # same objects reconfigured into a smaller epoch, like a shrinking
        # quorum (process_group_test.py:346-380 reconfiguration checks)
        colls = [
            CollectivesTcp(timeout=timedelta(seconds=10), hostname="localhost")
            for _ in range(3)
        ]

        def epoch1(rank):
            colls[rank].configure(f"{store.address()}/e1", rank, 3)
            a = np.ones(4, dtype=np.float32)
            return colls[rank].allreduce([a], ReduceOp.SUM).wait()[0]

        with ThreadPoolExecutor(max_workers=3) as ex:
            outs = list(ex.map(epoch1, range(3)))
        for out in outs:
            np.testing.assert_allclose(out, 3.0)

        def epoch2(rank):
            colls[rank].configure(f"{store.address()}/e2", rank, 2)
            a = np.ones(4, dtype=np.float32)
            out = colls[rank].allreduce([a], ReduceOp.SUM).wait()[0]
            colls[rank].shutdown()
            return out

        with ThreadPoolExecutor(max_workers=2) as ex:
            outs = list(ex.map(epoch2, range(2)))
        for out in outs:
            np.testing.assert_allclose(out, 2.0)
        colls[2].shutdown()


class TestWirePipeline:
    """Round-3 data-plane upgrades: bf16 wire compression, tag-matched
    receives surviving out-of-order concurrent p2p traffic, and windowed
    (≤k in flight) transfer pipelines — the host-path answer to the role
    NCCL's async streams play in the reference
    (process_group.py:431-447)."""

    def test_bf16_wire_allreduce(self, store):
        def fn(c, rank):
            arr = np.linspace(-3.0, 3.0, 4099, dtype=np.float32) * (rank + 1)
            return c.allreduce([arr], ReduceOp.AVG).wait(
                timedelta(seconds=20)
            )[0]

        outs = _run_world(store, 3, fn, prefix="bf16w", wire_dtype="bfloat16")
        expect = np.linspace(-3.0, 3.0, 4099, dtype=np.float32) * 2.0
        for out in outs:
            assert out.dtype == np.float32
            # bf16 has ~3 decimal digits; per-hop requantization over a
            # 3-ring stays within a few ulps of that
            np.testing.assert_allclose(out, expect, rtol=3e-2, atol=3e-2)
        # lossy wire must still be DETERMINISTICALLY lossy: every rank
        # holds the bitwise-identical result, or replica groups that use
        # bf16-wire gradient averaging silently diverge (round-3 advisor
        # high finding: the chunk owner kept full f32 while peers stored
        # the bf16-rounded copy)
        for out in outs[1:]:
            np.testing.assert_array_equal(out, outs[0])

    def test_bf16_wire_bitwise_identical_world4(self, store):
        # uneven chunks + SUM: same bitwise-equality invariant
        def fn(c, rank):
            rng = np.random.default_rng(17 + rank)
            arr = rng.standard_normal(7331).astype(np.float32)
            return c.allreduce([arr], ReduceOp.SUM).wait(
                timedelta(seconds=30)
            )[0]

        outs = _run_world(store, 4, fn, prefix="bf16bw4", wire_dtype="bfloat16")
        for out in outs[1:]:
            np.testing.assert_array_equal(out, outs[0])

    def test_out_of_order_tags_are_matched(self, store):
        # rank 0 sends tag B then tag A; rank 1 waits for A first: the
        # B frame must be stashed, not declared a desync
        def fn(c, rank):
            if rank == 0:
                c.send(np.full(4, 7.0, dtype=np.float32), dst=1, tag=22).wait()
                c.send(np.full(4, 5.0, dtype=np.float32), dst=1, tag=11).wait()
                return None
            a = np.zeros(4, dtype=np.float32)
            b = np.zeros(4, dtype=np.float32)
            wa = c.recv(a, src=0, tag=11)
            wb = c.recv(b, src=0, tag=22)
            wa.wait(timedelta(seconds=10))
            wb.wait(timedelta(seconds=10))
            return a, b

        outs = _run_world(store, 2, fn, prefix="ooo")
        a, b = outs[1]
        np.testing.assert_allclose(a, 5.0)
        np.testing.assert_allclose(b, 7.0)

    def test_windowed_p2p_pipeline(self, store):
        # ≤3 concurrent sends/recvs with per-buffer tags complete and land
        # in the right buffers (the checkpoint-transport schedule)
        n_bufs, size = 10, 2048

        def fn(c, rank):
            if rank == 0:
                works = []
                for i in range(n_bufs):
                    works.append(
                        c.send(
                            np.full(size, float(i), dtype=np.float32),
                            dst=1,
                            tag=100 + i,
                        )
                    )
                    while len(works) >= 3:
                        works.pop(0).wait(timedelta(seconds=10))
                for w in works:
                    w.wait(timedelta(seconds=10))
                return None
            bufs = [np.zeros(size, dtype=np.float32) for _ in range(n_bufs)]
            works = [
                c.recv(bufs[i], src=0, tag=100 + i) for i in range(n_bufs)
            ]
            for w in works:
                w.wait(timedelta(seconds=20))
            return bufs

        outs = _run_world(store, 2, fn, prefix="win")
        for i, buf in enumerate(outs[1]):
            np.testing.assert_allclose(buf, float(i))

    def test_concurrent_streams_soak(self, store):
        # 30 rounds of simultaneous ring allreduce + bidirectional windowed
        # p2p on the same socket pair: the stash must route every frame to
        # its op with no desync, leak, or value corruption
        rounds, nbuf = 30, 4

        def fn(c, rank):
            peer = 1 - rank
            for r in range(rounds):
                ring = np.full(1024, float(rank + 1 + r), dtype=np.float32)
                ar = c.allreduce([ring], ReduceOp.SUM)
                sends = [
                    c.send(
                        np.full(256, float(r * nbuf + i), dtype=np.float32),
                        dst=peer,
                        tag=(rank << 12) | (r * nbuf + i) & 0xFFF,
                    )
                    for i in range(nbuf)
                ]
                bufs = [np.zeros(256, dtype=np.float32) for _ in range(nbuf)]
                recvs = [
                    c.recv(
                        bufs[i],
                        src=peer,
                        tag=(peer << 12) | (r * nbuf + i) & 0xFFF,
                    )
                    for i in range(nbuf)
                ]
                ar.wait(timedelta(seconds=30))
                for w in sends + recvs:
                    w.wait(timedelta(seconds=30))
                np.testing.assert_array_equal(
                    ring, float((1 + r) + (2 + r)), err_msg=f"{rank}/{r}"
                )
                for i, buf in enumerate(bufs):
                    np.testing.assert_array_equal(
                        buf, float(r * nbuf + i), err_msg=f"{rank}/{r}/{i}"
                    )
            # stash drained: nothing parked once all ops completed
            for p in c._peers.values():
                assert p.stash_bytes == 0, p.stash
            return True

        assert all(_run_world(store, 2, fn, prefix="soak"))

    def test_bf16_wire_world4_uneven(self, store):
        # 4-rank ring with chunk sizes that don't divide evenly, compressed
        def fn(c, rank):
            arr = np.full(10007, float(rank + 1), dtype=np.float32)
            return c.allreduce([arr], ReduceOp.SUM).wait(
                timedelta(seconds=30)
            )[0]

        outs = _run_world(
            store, 4, fn, prefix="bf16w4", wire_dtype="bfloat16"
        )
        for out in outs:
            np.testing.assert_allclose(out, 10.0, rtol=2e-2)

    def test_p2p_overlaps_ring_traffic(self, store):
        # a checkpoint-style p2p transfer issued while ring allreduces run
        # on the op thread: tag matching keeps both streams intact
        def fn(c, rank):
            ring = np.full(4096, float(rank + 1), dtype=np.float32)
            ar = c.allreduce([ring], ReduceOp.SUM)
            if rank == 0:
                pw = c.send(np.arange(512, dtype=np.float32), dst=1, tag=9)
            else:
                side = np.zeros(512, dtype=np.float32)
                pw = c.recv(side, src=0, tag=9)
            ar.wait(timedelta(seconds=20))
            pw.wait(timedelta(seconds=20))
            return ring if rank == 0 else (ring, )

        outs = _run_world(store, 2, fn, prefix="olap")
        np.testing.assert_allclose(outs[0], 3.0)


class TestWedgedPeers:
    """Round-1 review weak #2: a dead/silent peer must not wedge the op
    thread forever, and teardown must not leak blocked threads
    (reference: process_group_test.py:346-397 reconfigure/leak checks)."""

    def _pair(self, store, timeout_s):
        colls = [
            CollectivesTcp(
                timeout=timedelta(seconds=timeout_s), hostname="localhost"
            )
            for _ in range(2)
        ]
        with ThreadPoolExecutor(max_workers=2) as ex:
            list(
                ex.map(
                    lambda r: colls[r].configure(
                        f"{store.address()}/wedge", r, 2
                    ),
                    range(2),
                )
            )
        return colls

    def test_silent_peer_times_out(self, store):
        import time

        c0, c1 = self._pair(store, timeout_s=1)
        try:
            # rank 1 never participates: rank 0's ring recv must fail with a
            # timeout within the configured deadline, not block forever
            a = np.ones(8, dtype=np.float32)
            t0 = time.monotonic()
            with pytest.raises(Exception):
                c0.allreduce([a], ReduceOp.SUM).wait(timedelta(seconds=5))
            assert time.monotonic() - t0 < 4.0
        finally:
            c0.shutdown()
            c1.shutdown()

    def test_shutdown_unblocks_wedged_op_and_leaks_no_threads(self, store):
        import threading
        import time

        def coll_threads():
            return [
                t
                for t in threading.enumerate()
                if t.name.startswith("tft_coll")
            ]

        baseline = len(coll_threads())
        c0, c1 = self._pair(store, timeout_s=30)
        a = np.ones(8, dtype=np.float32)
        work = c0.allreduce([a], ReduceOp.SUM)  # blocks: peer is silent
        queued = c0.allreduce([a.copy()], ReduceOp.SUM)  # parked behind it
        time.sleep(0.2)
        t0 = time.monotonic()
        c0.shutdown()  # must wake the blocked op and join the executor
        assert time.monotonic() - t0 < 5.0
        with pytest.raises(Exception):
            work.wait(timedelta(seconds=1))
        # the cancelled queued op must resolve too, not hang its waiter
        with pytest.raises(Exception):
            queued.wait(timedelta(seconds=1))
        c1.shutdown()
        deadline = time.monotonic() + 5
        while len(coll_threads()) > baseline and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(coll_threads()) <= baseline

    def test_repeated_reconfigure_leaks_no_threads(self, store):
        import threading
        import time

        before = threading.active_count()
        c = CollectivesTcp(timeout=timedelta(seconds=5), hostname="localhost")
        for epoch in range(5):
            c.configure(f"{store.address()}/re{epoch}", 0, 1)
            c.allreduce([np.ones(4, dtype=np.float32)]).wait()
        c.shutdown()
        deadline = time.monotonic() + 5
        while threading.active_count() > before and time.monotonic() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before + 1  # store client slack


class TestWrappers:
    def test_dummy(self):
        c = CollectivesDummy(rank=0, world_size=2)
        a = np.ones(3, dtype=np.float32)
        assert c.allreduce([a]).wait()[0] is a
        assert len(c.allgather(a).wait()) == 2
        c.configure("x", 0, 2)
        assert c.configure_count == 1

    def test_error_swallowing_latches(self, store):
        inner = CollectivesTcp(timeout=timedelta(seconds=5), hostname="localhost")
        wrap = ErrorSwallowingCollectives(inner)
        # not configured -> first op errors and latches; later ops no-op
        a = np.ones(3, dtype=np.float32)
        out = wrap.allreduce([a]).wait()
        assert wrap.error() is not None
        out2 = wrap.allreduce([a]).wait()
        assert out2 == [a]
        # reconfigure clears the latch
        wrap.configure(f"{store.address()}/esw", 0, 1)
        assert wrap.error() is None
        res = wrap.allreduce([a]).wait()
        np.testing.assert_allclose(res[0], 1.0)
        wrap.shutdown()


class TestNativePlane:
    """Round-4 native data plane (native/dataplane.cc): the NCCL-role
    striped C++ ring with one-copy CMA pulls for same-host peers. The
    default fixture path already exercises CMA (in-process ranks share a
    pid); these pin down the forced-TCP mode, routing introspection,
    bitwise bf16 on the striped wire, and peer-death attribution."""

    def test_plane_info_modes(self, store, monkeypatch):
        def fn(c, rank):
            return c.plane_info()

        assert set(_run_world(store, 2, fn, prefix="pi1")) == {"cma"}
        monkeypatch.setenv("TORCHFT_DP_CMA", "0")
        assert set(_run_world(store, 2, fn, prefix="pi2")) == {"tcp-striped"}
        assert set(
            _run_world(store, 2, fn, prefix="pi3", native_plane=False)
        ) == {"python-ring"}

    @pytest.mark.parametrize("world", [2, 3])
    def test_tcp_striped_matches_python_ring(self, store, monkeypatch, world):
        monkeypatch.setenv("TORCHFT_DP_CMA", "0")

        def fn(c, rank):
            assert c.plane_info() == "tcp-striped"
            rng = np.random.default_rng(5 + rank)
            a = rng.standard_normal(100003).astype(np.float32)
            b = a.copy()
            out = c.allreduce([a], ReduceOp.AVG).wait(timedelta(seconds=20))
            return b, out[0]

        outs = _run_world(store, world, fn, prefix=f"tsm{world}")
        expect = np.mean([b for b, _ in outs], axis=0)
        for _, got in outs:
            np.testing.assert_allclose(got, expect, rtol=1e-6, atol=1e-6)
        # all ranks bitwise identical (owner-chunk distribution invariant)
        for _, got in outs[1:]:
            np.testing.assert_array_equal(got, outs[0][1])

    def test_tcp_striped_bf16_wire_bitwise(self, store, monkeypatch):
        monkeypatch.setenv("TORCHFT_DP_CMA", "0")

        def fn(c, rank):
            rng = np.random.default_rng(23 + rank)
            a = rng.standard_normal(40961).astype(np.float32)
            return c.allreduce([a], ReduceOp.SUM).wait(
                timedelta(seconds=20)
            )[0]

        outs = _run_world(
            store, 3, fn, prefix="tsbf", wire_dtype="bfloat16"
        )
        for out in outs[1:]:
            np.testing.assert_array_equal(out, outs[0])

    def test_max_min_ops(self, store):
        def fn(c, rank):
            a = np.array([rank, -rank, 7], dtype=np.float32)
            mx = c.allreduce([a.copy()], ReduceOp.MAX).wait(
                timedelta(seconds=10)
            )[0]
            mn = c.allreduce([a.copy()], ReduceOp.MIN).wait(
                timedelta(seconds=10)
            )[0]
            return mx, mn

        outs = _run_world(store, 3, fn, prefix="mxmn")
        for mx, mn in outs:
            np.testing.assert_array_equal(mx, [2.0, 0.0, 7.0])
            np.testing.assert_array_equal(mn, [0.0, -2.0, 7.0])

    @pytest.mark.parametrize("cma", ["1", "0"])
    def test_peer_death_attribution(self, store, monkeypatch, cma):
        """A rank vanishing mid-allreduce surfaces PeerGoneError with the
        dead ring rank, on both the CMA and striped-TCP transports."""
        monkeypatch.setenv("TORCHFT_DP_CMA", cma)
        from torchft_tpu.collectives import PeerGoneError

        def fn(c, rank):
            if rank == 1:
                return "died"  # shutdown() in the harness closes sockets
            a = np.ones(1 << 20, dtype=np.float32)
            try:
                c.allreduce([a], ReduceOp.SUM).wait(timedelta(seconds=15))
                return "completed"
            except PeerGoneError as e:
                return ("gone", e.peer_rank)
            except Exception as e:  # noqa: BLE001
                return ("other", type(e).__name__, str(e)[:100])

        outs = _run_world(store, 2, fn, prefix=f"pd{cma}")
        assert outs[1] == "died"
        assert outs[0][0] == "gone", outs[0]
        assert outs[0][1] == 1


class TestCmaP2P:
    """Round-4 p2p CMA fast path: frames >= TORCHFT_CMA_P2P_MIN ship a
    pull descriptor instead of streaming bytes (heal transfers at memcpy
    class speed). The in-process fixture ranks share a pid, so the CMA
    negotiation arms the path."""

    def test_large_send_recv_roundtrip(self, store, monkeypatch):
        monkeypatch.setenv("TORCHFT_CMA_P2P_MIN", str(64 * 1024))
        n = 1 << 18  # 1 MB of f32 — above the lowered threshold

        def fn(c, rank):
            assert c.plane_info() == "cma"
            if rank == 0:
                payload = np.arange(n, dtype=np.float32)
                c.send(payload, dst=1, tag=77).wait(timedelta(seconds=20))
                return payload[:4].copy()
            buf = np.zeros(n, dtype=np.float32)
            c.recv(buf, src=0, tag=77).wait(timedelta(seconds=20))
            return buf[:4].copy()

        outs = _run_world(store, 2, fn, prefix="cmap2p")
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_large_frame_for_other_tag_is_stashed(self, store, monkeypatch):
        """A CMA descriptor for a tag nobody is waiting on yet must be
        pulled immediately (the sender's buffer is parked until the ack)
        and stashed for the later recv."""
        monkeypatch.setenv("TORCHFT_CMA_P2P_MIN", str(64 * 1024))
        n = 1 << 16  # 256 KB

        def fn(c, rank):
            if rank == 0:
                c.send(np.full(n, 7.0, np.float32), dst=1, tag=22).wait(
                    timedelta(seconds=20)
                )
                c.send(np.full(n, 5.0, np.float32), dst=1, tag=11).wait(
                    timedelta(seconds=20)
                )
                return None
            a = np.zeros(n, np.float32)
            b = np.zeros(n, np.float32)
            wa = c.recv(a, src=0, tag=11)
            wb = c.recv(b, src=0, tag=22)
            wa.wait(timedelta(seconds=20))
            wb.wait(timedelta(seconds=20))
            return float(a[0]), float(b[0])

        outs = _run_world(store, 2, fn, prefix="cmastash")
        assert outs[1] == (5.0, 7.0)

    def test_checkpoint_transport_rides_cma(self, store, monkeypatch):
        monkeypatch.setenv("TORCHFT_CMA_P2P_MIN", str(64 * 1024))
        from torchft_tpu.checkpointing.collectives_transport import (
            CollectivesTransport,
        )

        state = {"w": np.random.default_rng(3).standard_normal(1 << 18).astype(np.float32)}

        def fn(c, rank):
            t = CollectivesTransport(c, timeout=timedelta(seconds=20))
            if rank == 0:
                t.send_checkpoint([1], 0, state, timedelta(seconds=20))
                return None
            got = t.recv_checkpoint(0, t.metadata(), 0, timedelta(seconds=20))
            return np.asarray(got["w"])

        outs = _run_world(store, 2, fn, prefix="cmaheal")
        np.testing.assert_array_equal(outs[1], state["w"])

    def test_ack_timeout_quarantines_and_poisons(self, store, monkeypatch):
        """If the pull-ack never arrives, the sender must pin the buffer
        process-wide (a dangling descriptor may still be pulled later) and
        poison the stream — never surface a retryable timeout that lets
        the caller reuse the memory."""
        monkeypatch.setenv("TORCHFT_CMA_P2P_MIN", str(64 * 1024))
        import time

        import torchft_tpu.collectives as C

        before = len(C._CMA_QUARANTINE)
        n = 1 << 16

        def fn(c, rank):
            if rank == 1:
                time.sleep(3.0)  # never posts the recv inside the timeout
                return "slept"
            payload = np.full(n, 3.0, np.float32)
            try:
                c.send(payload, dst=1, tag=33).wait(timedelta(seconds=8))
                return "sent"
            except Exception as e:  # noqa: BLE001
                return type(e).__name__

        outs = _run_world(
            store, 2, fn, prefix="cmaq", timeout=timedelta(seconds=1)
        )
        assert outs[1] == "slept"
        # the send failed terminally (poisoned epoch), not retryably
        assert outs[0] in ("PeerGoneError", "ConnectionError"), outs
        assert len(C._CMA_QUARANTINE) == before + 1
        assert C._CMA_QUARANTINE[-1].nbytes == n * 4

    def test_pull_failure_latches_cma_off(self, store, monkeypatch):
        """Round-4 advisor medium: the negotiation probes only the ring-left
        neighbor, but a passing vote arms pulls between ARBITRARY pairs. If
        a pull then fails at op time (pairwise-asymmetric process_vm_readv
        permission), the process must latch CMA off so the NEXT epoch's
        negotiation converges the whole group to TCP — not retry into the
        same failure every epoch."""
        monkeypatch.setenv("TORCHFT_CMA_P2P_MIN", str(64 * 1024))
        import torchft_tpu._native as N
        import torchft_tpu.collectives as C

        monkeypatch.setattr(C, "_CMA_BROKEN", False)

        def broken(pid, addr, view):
            raise OSError(1, "Operation not permitted")

        monkeypatch.setattr(N, "cma_read_into", broken)
        n = 1 << 18

        def fn(c, rank):
            assert c.plane_info() == "cma"  # probe (cma_read) still passes
            got_err = False
            if rank == 0:
                try:
                    c.send(np.ones(n, np.float32), dst=1, tag=9).wait(
                        timedelta(seconds=15)
                    )
                except Exception:  # noqa: BLE001
                    got_err = True
            else:
                buf = np.zeros(n, np.float32)
                try:
                    c.recv(buf, src=0, tag=9).wait(timedelta(seconds=15))
                except Exception:  # noqa: BLE001
                    got_err = True
            # next epoch: the latch must force the WHOLE group to TCP,
            # and ops must work there with process_vm_readv still broken
            c.configure(f"{store.address()}/cmalatch2", rank, 2)
            plane2 = c.plane_info()
            out = c.allreduce(
                [np.full(4, float(rank + 1), np.float32)], ReduceOp.SUM
            ).wait(timedelta(seconds=15))
            return got_err, plane2, float(out[0][0])

        outs = _run_world(
            store, 2, fn, prefix="cmalatch", timeout=timedelta(seconds=5)
        )
        assert C._CMA_BROKEN is True
        # the receiver's pull failed; the sender's ack never arrived
        assert outs[0][0] and outs[1][0], outs
        assert outs[0][1] == "tcp-striped" and outs[1][1] == "tcp-striped"
        assert outs[0][2] == 3.0 and outs[1][2] == 3.0
