"""Flash-attention kernel numerics vs the reference jnp implementation
(interpreter mode on CPU; the same kernels compile for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu.ops.attention import attention
from torchft_tpu.ops.pallas.flash_attention import flash_attention


def qkv(b=2, s=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches(causal):
    q, k, v = qkv()
    expect = attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-5)


def test_grads_match():
    q, k, v = qkv(s=128)

    def loss_ref(q, k, v):
        return (attention(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2
        ).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_uneven_blocks_rejected():
    q, k, v = qkv(s=100)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_sharded_flash_in_model_matches_plain():
    """attention_impl='flash' under a dp×tp mesh (shard_map-wrapped pallas)
    must equal the plain GSPMD path."""
    import numpy as onp

    from torchft_tpu.models.transformer import TransformerConfig, init_params, loss_fn
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh

    base = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, dtype=jnp.float32,
    )
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    params = init_params(jax.random.PRNGKey(0), TransformerConfig(**base))
    tokens = jnp.asarray(
        onp.random.default_rng(0).integers(0, 64, (4, 128)), jnp.int32
    )
    losses = {}
    for impl in ("flash", "plain"):
        cfg = TransformerConfig(**base, attention_impl=impl)
        with jax.set_mesh(mesh):
            losses[impl] = float(
                jax.jit(lambda p, t, c=cfg: loss_fn(p, t, c, mesh))(params, tokens)
            )
    assert abs(losses["flash"] - losses["plain"]) < 1e-3


def test_bad_attention_impl_rejected():
    from torchft_tpu.models.transformer import TransformerConfig, _use_flash

    with pytest.raises(ValueError, match="attention_impl"):
        _use_flash(TransformerConfig(attention_impl="xla"), 4096)


def test_use_flash_auto_threshold(monkeypatch):
    """The auto rule (the 46x fix): flash only past the per-chip
    scores-memory ceiling; per-chip = global / (dp·fsdp batch shards,
    tp head shards)."""
    from unittest.mock import patch

    import jax.numpy as jnp

    from torchft_tpu.models import transformer as T

    cfg = T.TransformerConfig(attention_impl="auto", n_heads=8, dtype=jnp.bfloat16)

    class FakeMesh:
        def __init__(self, **shape):
            self.shape = shape

    with patch.object(T.jax, "default_backend", return_value="tpu"):
        # b1 h8 s8192: 4 * 8 * 8192^2 = 2.1 GB < 4 GB -> plain (the fix)
        assert not T._use_flash(cfg, 8192, 1)
        # b1 h8 s32768: 34 GB -> flash (the memory-ceiling role)
        assert T._use_flash(cfg, 32768, 1)
        # global b8 would cross the ceiling, but dp=4 shards it 4-way:
        # per-chip 4.3 GB... / 4 = 1.07... scaled: 4*2*8*8192^2 = 4.3 GB
        # per chip at dp=4 -> just over; at dp=8 -> under
        assert not T._use_flash(cfg, 8192, 8, FakeMesh(dp=8))
        assert T._use_flash(cfg, 8192, 32, FakeMesh(dp=2))
        # tp shards heads
        assert not T._use_flash(cfg, 16384, 1, FakeMesh(tp=8))
        # threshold env override
        monkeypatch.setenv("TORCHFT_TPU_FLASH_SCORES_GB", "0.5")
        assert T._use_flash(cfg, 8192, 1)
        monkeypatch.setenv("TORCHFT_TPU_FLASH_SCORES_GB", "not-a-number")
        assert not T._use_flash(cfg, 8192, 1)  # malformed -> default 4 GB
    # non-tpu backend never chooses the pallas kernel
    with patch.object(T.jax, "default_backend", return_value="cpu"):
        assert not T._use_flash(cfg, 32768, 1)


def test_chunked_loss_matches_dense(monkeypatch):
    """Long-context loss head: chunked cross entropy (scan over the
    unembed, [S,V] logits never materialized) must match the dense path
    to f32 accumulation noise in value and grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2,
        head_dim=16, d_ff=64, dtype=jnp.float32,
    )
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32
    )

    dense = T.loss_fn(params, tokens, cfg, None)
    g_dense = jax.grad(lambda p: T.loss_fn(p, tokens, cfg, None))(params)

    monkeypatch.setenv("TORCHFT_TPU_LOSS_CHUNK_ELEMS", "64")  # force chunking
    chunked = T.loss_fn(params, tokens, cfg, None)
    g_chunk = jax.grad(lambda p: T.loss_fn(p, tokens, cfg, None))(params)

    np.testing.assert_allclose(float(dense), float(chunked), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_dense), jax.tree_util.tree_leaves(g_chunk)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestChunkedAttention:
    """Round-4 tiered chunked-scan attention: the pure-XLA long-context
    path (s=8192: 15% -> ~31% MFU on v5e). Must be numerically the same
    attention as the plain reference, including across tier boundaries
    and under grad."""

    def _qkv(self, s, b=2, h=4, d=32, seed=0):
        import jax

        key = jax.random.PRNGKey(seed)
        ks = jax.random.split(key, 3)
        shp = (b, s, h, d)
        return tuple(jax.random.normal(k, shp, jnp.float32) for k in ks)

    @pytest.mark.parametrize("s,chunk,tiers", [(512, 128, 4), (256, 64, 1), (384, 64, 3)])
    def test_matches_plain(self, s, chunk, tiers):
        from torchft_tpu.ops.attention import attention, chunked_attention

        q, k, v = self._qkv(s)
        ref = attention(q, k, v, causal=True)
        got = chunked_attention(q, k, v, causal=True, chunk=chunk, tiers=tiers)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_non_causal_matches(self):
        from torchft_tpu.ops.attention import attention, chunked_attention

        q, k, v = self._qkv(256)
        ref = attention(q, k, v, causal=False)
        got = chunked_attention(q, k, v, causal=False, chunk=64)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_grad_matches_plain(self):
        import jax

        from torchft_tpu.ops.attention import attention, chunked_attention

        q, k, v = self._qkv(256)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v, causal=True) ** 2).sum()

        gref = jax.grad(loss(attention), argnums=(0, 1, 2))(q, k, v)
        gchk = jax.grad(
            lambda q, k, v: (
                chunked_attention(q, k, v, causal=True, chunk=64, tiers=4) ** 2
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gref, gchk):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_model_routes_chunked(self, monkeypatch):
        """attention_impl='chunked' trains; auto engages past the S
        threshold (lowered via env for a CPU-sized check)."""
        import jax
        import optax

        from torchft_tpu.models.transformer import (
            TransformerConfig,
            _use_chunked,
        )
        from torchft_tpu.parallel.train_step import TrainStep
        from torchft_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = TransformerConfig(
            vocab_size=128,
            d_model=64,
            n_layers=2,
            n_heads=4,
            head_dim=16,
            d_ff=128,
            dtype=jnp.float32,
            attention_impl="chunked",
        )
        assert _use_chunked(cfg, 512)
        monkeypatch.setenv("TORCHFT_TPU_ATTN_CHUNKED_MIN_S", "512")
        auto = TransformerConfig(**{**cfg.__dict__, "attention_impl": "auto"})
        assert _use_chunked(auto, 512)
        assert not _use_chunked(auto, 256)

        mesh = make_mesh(MeshConfig())
        ts = TrainStep(cfg, optax.adam(1e-2), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt = ts.init_opt(params)
        tokens = ts.shard_batch(
            jnp.asarray(
                np.random.default_rng(0).integers(0, 128, (2, 512)), jnp.int32
            )
        )
        loss, _, _ = ts.step(params, opt, tokens)
        assert np.isfinite(float(loss))
