"""Periodic disk checkpoint → total failure → resume (reference workflow:
train_ddp.py:141-148 + manager.py:83-85 docs — save manager + model +
optimizer + dataloader state frequently; a fully restarted job continues
from disk instead of step 0).

Drives examples/train_ddp.py as real subprocesses: a straight 6-step run
is the reference; a 3-step run that checkpoints each step, then a fresh
process resuming to step 6, must end with a bit-identical param checksum.
"""

import os
import re
import subprocess
import sys

import pytest

from torchft_tpu.coordination import LighthouseServer

# multi-process soak tier: excluded from the default run (pyproject addopts)
pytestmark = pytest.mark.soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_trainer(lighthouse_addr: str, steps: int, ckpt_dir=None) -> str:
    env = dict(os.environ)
    env.update(
        TORCHFT_LIGHTHOUSE=lighthouse_addr,
        REPLICA_GROUP_ID="0",
        NUM_REPLICA_GROUPS="1",
        STEPS=str(steps),
        JAX_PLATFORMS="cpu",
    )
    if ckpt_dir:
        env.update(CKPT_DIR=str(ckpt_dir), CKPT_EVERY="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_ddp.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=180,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stderr + proc.stdout  # logging goes to stderr


def _checksum(log: str) -> str:
    m = re.search(r"done: step=(\d+) param_checksum=(-?\d+\.\d+)", log)
    assert m, log[-2000:]
    return m.group(1), m.group(2)


def test_disk_checkpoint_resume_bit_identical(tmp_path):
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=1)
    addr = lighthouse.address().split("//", 1)[-1]
    try:
        # reference: one continuous 6-step run
        ref_log = _run_trainer(addr, steps=6)
        ref_step, ref_sum = _checksum(ref_log)
        assert ref_step == "6"

        # run to step 3 with per-step checkpoints, "lose everything"
        # (process exits; nothing survives but the checkpoint dir)
        first_log = _run_trainer(addr, steps=3, ckpt_dir=tmp_path)
        step3, _ = _checksum(first_log)
        assert step3 == "3"
        assert (tmp_path / "group0_step3.ckpt").exists()

        # a fresh process resumes from disk and continues to step 6
        resumed_log = _run_trainer(addr, steps=6, ckpt_dir=tmp_path)
        assert "resumed from" in resumed_log and "at step 3" in resumed_log
        # the step counter continued (first committed step is 4, not 1)
        first_commit = re.search(r"step=(\d+) batches_committed", resumed_log)
        assert first_commit and first_commit.group(1) == "4", resumed_log[-2000:]

        end_step, end_sum = _checksum(resumed_log)
        assert end_step == "6"
        # params + optimizer state + sampler position all round-tripped:
        # the resumed run is bit-identical to the continuous one
        assert end_sum == ref_sum
    finally:
        lighthouse.shutdown()
