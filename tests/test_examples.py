"""End-to-end example configs from BASELINE.md: "DiLoCo 4 groups" and the
HSDP composition, driven as real subprocesses against an in-process
lighthouse, asserting cross-group state convergence (the reference's
integ-test bar: state-dict equality across groups)."""

import os
import re
import subprocess
import sys
import pytest
from concurrent.futures import ThreadPoolExecutor

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.store import StoreServer

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
from conftest import scaled_timeout, skip_if_known_corruption

pytestmark = pytest.mark.soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_groups(script: str, num_groups: int, extra_env: dict, min_replicas=None):
    lighthouse = LighthouseServer(
        bind="[::]:0", min_replicas=min_replicas or num_groups
    )
    stores = [StoreServer() for _ in range(num_groups)]
    try:

        def run(g):
            env = dict(os.environ)
            env.update(
                TORCHFT_LIGHTHOUSE=lighthouse.address(),
                TORCHFT_STORE_ADDR=stores[g].address(),
                REPLICA_GROUP_ID=str(g),
                NUM_REPLICA_GROUPS=str(num_groups),
                RANK="0",
                WORLD_SIZE="1",
                JAX_PLATFORMS="cpu",
            )
            env.update(extra_env)
            return subprocess.run(
                [sys.executable, os.path.join(REPO, "examples", script)],
                env=env,
                capture_output=True,
                text=True,
                timeout=scaled_timeout(240),
                cwd=REPO,
            )

        with ThreadPoolExecutor(max_workers=num_groups) as pool:
            procs = list(pool.map(run, range(num_groups)))
        if any(p.returncode != 0 for p in procs):
            # Gather ALL workers before judging: one worker dying of the
            # documented pre-existing native corruption (ROADMAP open
            # item) cascades into quorum timeouts on its peers, and only
            # the ROOT death carries the interesting evidence — shared
            # policy in conftest.skip_if_known_corruption.
            skip_if_known_corruption(
                "".join(p.stderr for p in procs),
                rcs=[p.returncode for p in procs],
            )
            bad = next(p for p in procs if p.returncode != 0)
            raise AssertionError(
                f"worker rc={bad.returncode}: {bad.stderr[-3000:]}"
            )
        return [p.stderr + p.stdout for p in procs]
    finally:
        for s in stores:
            s.shutdown()
        lighthouse.shutdown()


def _checksums(logs, pattern=r"param_checksum=(-?\d+\.\d+)"):
    sums = []
    for log in logs:
        m = re.search(pattern, log)
        assert m, log[-2000:]
        sums.append(m.group(1))
    return sums


def test_diloco_four_groups():
    logs = _run_groups(
        "train_diloco.py",
        num_groups=4,
        extra_env={"OUTER_STEPS": "2", "SYNC_EVERY": "2"},
    )
    sums = _checksums(logs)
    # outer steps averaged pseudogradients across all 4 groups: identical
    # outer state everywhere (bit-identical, reference integ-test bar)
    assert len(set(sums)) == 1, sums


def test_hsdp_example_two_groups():
    logs = _run_groups(
        "train_hsdp.py",
        num_groups=2,
        extra_env={
            "STEPS": "3",
            "DEVICES_PER_GROUP": "4",
            "FSDP": "2",
            "TP": "2",
        },
    )
    sums = _checksums(logs)
    assert len(set(sums)) == 1, sums


def test_resnet_cifar_two_groups(tmp_path):
    """BASELINE.md config: "ResNet-18 CIFAR-10 DDP" — conv model family
    through the full FT loop, bit-identical params across groups."""
    logs = _run_groups(
        "train_cifar.py",
        num_groups=2,
        extra_env={
            "STEPS": "3",
            "BATCH": "8",
            "DATA_PATH": str(tmp_path / "cifar.npz"),
        },
    )
    sums = _checksums(logs)
    assert len(set(sums)) == 1, sums
