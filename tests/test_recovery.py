"""Recovery-envelope test: the wall-clock bound the reference encodes in
assertions (lighthouse_test.py:44-47 quorum < 0.4s; manager_integ_test.py:
325-368 deadline enforcement < 1s) — here measured on the full kill/heal
path with real process kills (torchft_tpu/benchmarks/recovery.py).

Bounds are deliberately loose multiples of the configured detection
cadence (1s op timeout, 1s heartbeat lease) so the test is about the
*mechanism* (bounded detection + flush re-quorum + heal), not scheduler
luck.
"""

import pytest

from torchft_tpu.benchmarks.recovery import measure_recovery

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
pytestmark = pytest.mark.soak


def test_recovery_envelope():
    r = measure_recovery(
        total_steps=25,
        kill_at_step=6,
        step_sleep=0.05,
        op_timeout=1.0,
        heartbeat_timeout_ms=1000,
        timeout_s=120.0,
    )
    # survivor: one wedged op (<= op timeout) + flush re-quorum; 6s allows
    # a heartbeat-lease wait plus CI scheduling noise
    assert r.survivor_blackout_s < 6.0, r
    # rejoiner: exec + store bootstrap + quorum join + live heal + 1 step
    assert r.rejoin_to_commit_s < 20.0, r
    # the envelope in step units: the survivor must keep committing —
    # after the blackout it may not silently skip further steps
    assert r.steady_step_s > 0


def test_recovery_1of4_north_star_shape():
    """BASELINE north star: survive killing 1-of-4 replica groups. The
    three survivors must keep committing through the blackout and the
    victim must rejoin and commit."""
    r = measure_recovery(
        total_steps=25,
        kill_at_step=6,
        step_sleep=0.05,
        op_timeout=1.0,
        heartbeat_timeout_ms=1000,
        timeout_s=120.0,
        num_groups=4,
    )
    assert r.survivor_blackout_s < 6.0, r
    assert r.rejoin_to_commit_s < 20.0, r


def test_recovery_1of4_one_step_envelope():
    """Round-4: with the death watch (socket-FIN-driven evict + early
    re-quorum overlapping the doomed step), killing 1-of-4 groups must
    cost the survivors at most ONE committed step (the reference's
    product promise, README.md:29-47). The bench box can be contended,
    so one retry is allowed — but it is LOGGED and every run's envelope
    lands in the failure message, so a silently-degrading envelope shows
    up as retry noise in CI history instead of being masked (round-4
    review weak #6)."""
    import warnings

    runs = []
    for attempt in range(2):
        r = measure_recovery(
            total_steps=25,
            kill_at_step=6,
            step_sleep=0.05,
            op_timeout=1.0,
            heartbeat_timeout_ms=1000,
            timeout_s=120.0,
            num_groups=4,
        )
        runs.append(r.as_dict())
        if r.survivor_steps_lost <= 1:
            break
        warnings.warn(
            f"recovery envelope attempt {attempt} exceeded 1 lost step: "
            f"{runs[-1]} (retrying once; a persistent retry pattern here "
            "means the envelope is degrading)",
            stacklevel=1,
        )
    assert runs[-1]["survivor_steps_lost"] <= 1, {"all_attempts": runs}
    # the blackout itself (not just net lost steps) must stay bounded:
    # the death watch's early re-quorum should land the survivor's first
    # post-kill commit within ~2 steady steps even on a contended box
    assert runs[-1]["blackout_steps"] <= 4.0, {"all_attempts": runs}
