"""End-to-end integration: multi-replica-group training in one process.

Ports the reference's Runner/TrainLoop harness (manager_integ_test.py):
real C++ lighthouse + manager servers on localhost, replica groups as
threads, TCP collectives across groups, HTTP checkpoint recovery, and
failure injection as exceptions at chosen (rank, step) with torchelastic-
style restart attempts.
"""

import logging
import threading
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np
import pytest

from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.optim import ManagedOptimizer
from torchft_tpu.store import StoreServer

logger = logging.getLogger(__name__)


class InjectedFailure(Exception):
    pass


class FailureInjector:
    """Thread-safe (rank, step) -> raise-once failure injection
    (manager_integ_test.py:43-61)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._failures: Set[Tuple[int, int]] = set()
        self.count = 0

    def fail_at(self, rank: int, step: int) -> "FailureInjector":
        with self._lock:
            self._failures.add((rank, step))
            return self

    def check(self, rank: int, step: int) -> None:
        with self._lock:
            key = (rank, step)
            if key in self._failures:
                self.count += 1
                self._failures.remove(key)
                logger.warning("injecting failure rank=%s step=%s", rank, step)
                raise InjectedFailure(f"injected failure {rank=} {step=}")


@dataclass
class Runner:
    """One replica group: a store server + world_size rank threads, restarted
    up to ``attempts`` times on injected failure (torchelastic analogue)."""

    replica_id: int
    lighthouse_address: str
    failure_injector: FailureInjector
    train_loop: Callable[..., Dict[str, Any]]
    world_size: int = 1
    attempts: int = 3
    manager_args: Dict[str, Any] = field(default_factory=dict)
    train_loop_args: Dict[str, Any] = field(default_factory=dict)

    def _replica_main(self) -> List[Dict[str, Any]]:
        store = StoreServer()
        try:
            with ThreadPoolExecutor(
                max_workers=self.world_size,
                thread_name_prefix=f"replica{self.replica_id}",
            ) as executor:
                futures = [
                    executor.submit(
                        self.train_loop,
                        rank=rank,
                        store_addr=store.address(),
                        runner=self,
                    )
                    for rank in range(self.world_size)
                ]
                for fut in as_completed(futures):
                    fut.result()  # surface the first failure
                return [fut.result() for fut in futures]
        finally:
            store.shutdown()

    def run_replica(self) -> List[Dict[str, Any]]:
        for i in range(self.attempts):
            try:
                logger.info(
                    "starting replica group %s attempt %s", self.replica_id, i
                )
                return self._replica_main()
            except InjectedFailure as e:
                logger.info("got injected failure %s %s", i, e)
                if i == self.attempts - 1:
                    raise
                continue
        raise RuntimeError("ran out of attempts")


def _init_params(seed: int = 42) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "w": rng.standard_normal((3, 4)).astype(np.float32),
        "b": np.zeros(4, dtype=np.float32),
    }


def _loss_fn(params, x, y):
    import jax.numpy as jnp

    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def ddp_train_loop(
    rank: int, store_addr: str, runner: Runner, total_steps: int = 4
) -> Dict[str, Any]:
    import jax
    import optax

    total_steps = runner.train_loop_args.get("total_steps", total_steps)
    if runner.train_loop_args.get("device_plane"):
        # in-process groups over the DEVICE data plane ('ft' psum on the
        # virtual CPU mesh) instead of host TCP — the chaos soak runs the
        # same kill-ish schedule on every plane (round-4 review #10)
        from torchft_tpu.collectives_device import CollectivesDevice

        collectives = CollectivesDevice(timeout=timedelta(seconds=10))
    else:
        collectives = CollectivesTcp(timeout=timedelta(seconds=10))
    extra = {}
    if runner.train_loop_args.get("collectives_transport"):
        # heal over the data plane itself (the PGTransport role,
        # reference pg_transport.py) instead of the default HTTP server
        from torchft_tpu.checkpointing.collectives_transport import (
            CollectivesTransport,
        )

        extra["checkpoint_transport"] = CollectivesTransport(
            collectives, timeout=timedelta(seconds=10)
        )
    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # wired by ManagedOptimizer.init
        state_dict=None,
        min_replica_size=2,
        replica_id=str(runner.replica_id),
        store_addr=store_addr,
        rank=rank,
        world_size=runner.world_size,
        lighthouse_addr=runner.lighthouse_address,
        timeout=timedelta(seconds=10),
        quorum_timeout=timedelta(seconds=30),
        **extra,
        **runner.manager_args,
    )
    if "checkpoint_transport" in extra:
        # the heal really rides the injected transport, not an HTTP
        # fallback (metadata is what quorum peers fetch from)
        assert manager._checkpoint_transport is extra["checkpoint_transport"]
        assert manager._checkpoint_transport.metadata() == "<collectives>"
    try:
        opt = ManagedOptimizer(manager, optax.sgd(0.05))
        opt.init(_init_params())
        grad_fn = jax.jit(jax.grad(_loss_fn))
        # device plane: each group's arrays must live on ITS OWN device
        # (on hardware each group owns distinct chips; here one device of
        # the virtual mesh per group). Re-pin every step — a heal hands
        # back host arrays that would otherwise drift to the default
        # device and collide with the other group's 'ft' stacking.
        dev = (
            jax.devices()[runner.replica_id % jax.device_count()]
            if runner.train_loop_args.get("device_plane")
            else None
        )

        data_rng = np.random.default_rng(1000 + runner.replica_id * 17 + rank)
        while True:
            opt.begin_step()
            x = data_rng.standard_normal((8, 3)).astype(np.float32)
            y = data_rng.standard_normal((8, 4)).astype(np.float32)
            if dev is not None:
                x, y = jax.device_put((x, y), dev)
                grads = grad_fn(jax.device_put(opt.params, dev), x, y)
            else:
                grads = grad_fn(opt.params, x, y)
            opt.step(grads)

            if manager.current_step() >= total_steps:
                break
            runner.failure_injector.check(rank, manager.current_step())

        return {
            "params": jax.tree_util.tree_map(np.asarray, opt.params),
            "step": manager.current_step(),
        }
    finally:
        manager.shutdown(wait=False)


def _run_groups(
    lighthouse: LighthouseServer,
    injectors: List[FailureInjector],
    world_size: int = 1,
    manager_args: Optional[Dict[str, Any]] = None,
    train_loop_args: Optional[Dict[str, Any]] = None,
) -> List[List[Dict[str, Any]]]:
    num_replicas = len(injectors)
    with ThreadPoolExecutor(max_workers=num_replicas) as executor:
        futures = [
            executor.submit(
                Runner(
                    replica_id=replica_id,
                    lighthouse_address=lighthouse.address(),
                    failure_injector=injector,
                    train_loop=ddp_train_loop,
                    world_size=world_size,
                    manager_args=manager_args or {},
                    train_loop_args=train_loop_args or {},
                ).run_replica
            )
            for replica_id, injector in enumerate(injectors)
        ]
        return [f.result(timeout=120) for f in futures]


def assert_rank_states_equal(results: List[List[Dict[str, Any]]]) -> None:
    """Rank-lane r of every group must hold bit-identical params."""
    for rank in range(len(results[0])):
        ref = results[0][rank]["params"]
        for group in results[1:]:
            for key in ref:
                np.testing.assert_array_equal(ref[key], group[rank]["params"][key])


def test_ddp_healthy():
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        results = _run_groups(lighthouse, [FailureInjector(), FailureInjector()])
    finally:
        lighthouse.shutdown()
    assert_rank_states_equal(results)
    assert all(r["step"] >= 4 for group in results for r in group)


@pytest.mark.parametrize("use_async_quorum", [True, False])
@pytest.mark.parametrize("collectives_transport", [False, True])
def test_ddp_recovery(use_async_quorum, collectives_transport):
    """Recovery with the default HTTP transport and with the heal routed
    over the data plane itself (CollectivesTransport — the PGTransport
    role: windowed per-buffer sends on the freshly configured epoch)."""
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    injectors = [FailureInjector(), FailureInjector().fail_at(0, 2)]
    try:
        results = _run_groups(
            lighthouse,
            injectors,
            manager_args={"use_async_quorum": use_async_quorum},
            train_loop_args={"collectives_transport": collectives_transport},
        )
    finally:
        lighthouse.shutdown()
    assert_rank_states_equal(results)
    assert injectors[1].count == 1


def test_fixed_with_spares_promotion():
    """WorldSizeMode.FIXED_WITH_SPARES, 3 groups, min_replica_size=2: the
    third group is a hot spare contributing zeros; when a primary dies
    permanently (no restart — the rejoin path is covered by
    test_ddp_recovery) the spare promotes into its slot and training
    continues with the SAME divisor/effective batch size
    (manager.py:55-70 semantics, integration-tested here)."""
    from torchft_tpu.manager import WorldSizeMode

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    injectors = [
        FailureInjector(),
        FailureInjector().fail_at(0, 2),
        FailureInjector(),
    ]
    try:
        with ThreadPoolExecutor(max_workers=3) as executor:
            futures = [
                executor.submit(
                    Runner(
                        replica_id=i,
                        lighthouse_address=lighthouse.address(),
                        failure_injector=inj,
                        train_loop=ddp_train_loop,
                        # the dying group stays dead: promotion must carry
                        # the job without it
                        attempts=1,
                        manager_args={
                            "world_size_mode": WorldSizeMode.FIXED_WITH_SPARES,
                        },
                    ).run_replica
                )
                for i, inj in enumerate(injectors)
            ]
            survivors = [futures[0].result(timeout=120)]
            with pytest.raises(InjectedFailure):
                futures[1].result(timeout=120)
            survivors.append(futures[2].result(timeout=120))
    finally:
        lighthouse.shutdown()
    # primary 0 and the promoted spare finished in lockstep
    ref = survivors[0][0]
    other = survivors[1][0]
    assert ref["step"] >= 4 and other["step"] >= 4
    for key in ref["params"]:
        np.testing.assert_array_equal(ref["params"][key], other["params"][key])
    assert injectors[1].count == 1


def test_ddp_recovery_multi_rank():
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    # both ranks of the group die together (a half-dead group can only be
    # cleared by the quorum timeout, so the reference also kills whole groups)
    injectors = [FailureInjector(), FailureInjector().fail_at(0, 2).fail_at(1, 2)]
    try:
        results = _run_groups(lighthouse, injectors, world_size=2)
    finally:
        lighthouse.shutdown()
    assert_rank_states_equal(results)
    assert injectors[1].count == 2


def test_store_epoch_gc_soak():
    """Hundreds of data-plane flush re-quorums must not grow the store:
    every epoch writes coll/addr keys under torchft/{quorum_id}/ and the
    round-2 review found nothing ever deleted them (weak #5). Rank 0 now
    sweeps stale epochs on every reconfigure; after the soak, at most the
    current and previous epochs' keys may remain on any store."""
    from torchft_tpu.store import StoreClient

    lighthouse = LighthouseServer(
        bind="[::]:0", min_replicas=2, join_timeout_ms=100
    )
    stores = [StoreServer(), StoreServer()]
    rounds = 150
    errors: List[BaseException] = []

    def loop(gid: int) -> None:
        manager = Manager(
            collectives=CollectivesTcp(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {"x": 1},
            min_replica_size=2,
            replica_id=f"g{gid}",
            store_addr=stores[gid].address(),
            rank=0,
            world_size=1,
            lighthouse_addr=lighthouse.address(),
            timeout=timedelta(seconds=10),
            quorum_timeout=timedelta(seconds=30),
        )
        try:
            for _ in range(rounds):
                manager.start_quorum()
                manager.wait_quorum()
                if manager.current_step() == 0:
                    # clean bootstrap first: committing once completes the
                    # step-0 heal, so the flush rounds below never need the
                    # checkpoint path again (both groups stay at equal step)
                    manager.allreduce(np.ones(4, np.float32)).wait()
                    manager.should_commit()
                    continue
                # force a data-plane flush: the latched error fails the
                # commit, and the next quorum bumps quorum_id for everyone
                manager.report_error(RuntimeError("forced flush"))
                assert manager.should_commit() is False
        except BaseException as e:  # noqa: BLE001 — surface on main thread
            errors.append(e)
            raise
        finally:
            manager.shutdown(wait=False)

    try:
        threads = [
            threading.Thread(target=loop, args=(gid,)) for gid in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "soak worker wedged"
        assert not errors, errors
        for store in stores:
            client = StoreClient(store.address())
            keys = [
                k if isinstance(k, str) else k.decode()
                for k in client.keys("torchft/")
            ]
            epochs = {int(k.split("/")[1]) for k in keys}
            assert len(epochs) <= 2, f"stale epochs leaked: {sorted(epochs)}"
            # per rank per epoch: coll/addr + dpaddr + dpcma + dpcmaok
            # (4 keys) × 2 ranks × ≤2 live epochs
            assert len(keys) <= 16, f"store keys leaked: {len(keys)}"
            client.close()
    finally:
        lighthouse.shutdown()
        for store in stores:
            store.shutdown()


def test_quorum_timeout():
    """start_quorum with a tiny deadline on an unformable quorum returns a
    TimeoutError quickly (manager_integ_test.py:325-368 analogue)."""
    import time

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)  # never forms
    store = StoreServer()
    manager = None
    try:
        manager = Manager(
            collectives=CollectivesTcp(timeout=timedelta(seconds=5)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=2,
            replica_id="solo",
            store_addr=store.address(),
            rank=0,
            world_size=1,
            lighthouse_addr=lighthouse.address(),
            connect_timeout=timedelta(seconds=5),
        )
        t0 = time.perf_counter()
        manager.start_quorum(timeout=timedelta(milliseconds=100))
        with pytest.raises(TimeoutError):
            manager.wait_quorum()
        assert time.perf_counter() - t0 < 2.0
    finally:
        if manager is not None:
            manager.shutdown(wait=False)
        store.shutdown()
        lighthouse.shutdown()


def test_pipelined_multibucket_averaging():
    """Round-3: the host path's per-bucket pipeline (D2H ‖ ring ‖ H2D)
    must produce exact averages across groups with many buckets in
    flight, device-array inputs coming back as device arrays."""
    import jax
    import jax.numpy as jnp

    from torchft_tpu.ddp import allreduce_gradients

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    n_leaves = 6

    def one_group(gid: int):
        store = StoreServer()
        manager = Manager(
            collectives=CollectivesTcp(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=2,
            replica_id=f"pipe{gid}",
            store_addr=store.address(),
            rank=0,
            world_size=1,
            lighthouse_addr=lighthouse.address(),
            timeout=timedelta(seconds=10),
            # sync quorum: every group participates from step 0 (the async
            # bootstrap group-heal gate is covered by test_ddp_recovery)
            use_async_quorum=False,
        )
        try:
            grads = {
                f"g{i}": jnp.full((64, 3), float(gid * 10 + i), jnp.float32)
                for i in range(n_leaves)
            }
            manager.start_quorum()
            # 256-byte buckets force one bucket per leaf: ≥6 pipelined ops
            avg = allreduce_gradients(manager, grads, bucket_bytes=256)
            committed = manager.should_commit()
            return avg, committed
        finally:
            manager.shutdown(wait=False)
            store.shutdown()

    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(one_group, range(2)))

    for avg, committed in outs:
        assert committed
        for i in range(n_leaves):
            # mean of gid 0 and 1 leaves: (i + 10+i)/2 = i + 5
            leaf = avg[f"g{i}"]
            assert isinstance(leaf, jax.Array)  # H2D already dispatched
            np.testing.assert_allclose(np.asarray(leaf), float(i + 5))
    lighthouse.shutdown()


def test_epoch_gc_spares_previous_epoch_for_late_dialers():
    """Adversarial GC (round-3 review weak #6): the sweep runs WHILE a
    straggler group is still dialing the PREVIOUS epoch's rendezvous keys.
    The one-epoch slack rule must leave epoch current-1 intact (the
    straggler completes its mesh) while epochs <= current-2 are removed."""
    import threading
    import time
    from datetime import timedelta

    from torchft_tpu.collectives import CollectivesTcp, ReduceOp
    from torchft_tpu.manager import Manager, _ManagerLogger
    from torchft_tpu.store import StoreClient, StoreServer

    store = StoreServer()
    addr = store.address()
    client = StoreClient(addr)
    try:
        # a dead epoch (3) and the previous epoch (4); current is 5
        client.set("torchft/3/0/coll/addr/0", "stale:1")
        client.set("torchft/3/0/coll/dpaddr/0", "stale:1")

        prefix4 = f"{addr}/torchft/4/0"
        results = {}

        def straggler():
            c = CollectivesTcp(timeout=timedelta(seconds=20), hostname="localhost")
            try:
                c.configure(prefix4, 1, 2)  # blocks on coll/addr/0
                out = c.allreduce(
                    [np.full(8, 2.0, dtype=np.float32)], ReduceOp.SUM
                ).wait(timedelta(seconds=10))
                results["straggler"] = float(out[0][0])
            except Exception as e:  # noqa: BLE001
                results["straggler"] = repr(e)
            finally:
                c.shutdown()

        t = threading.Thread(target=straggler)
        t.start()
        time.sleep(0.3)  # straggler is now long-polling epoch 4's keys

        # the sweep fires mid-dial (rank 0 of some group reconfiguring
        # for epoch 5); stub carries just what the method touches
        class _MgrStub:
            def current_step(self):
                return 0

        class _Stub:
            pass

        stub = _Stub()
        stub._store = client
        stub._logger = _ManagerLogger.__new__(_ManagerLogger)
        stub._logger._manager = _MgrStub()  # warn path needs current_step
        stub._logger._replica_id = "gc"
        stub._logger._rank = 0
        Manager._sweep_stale_epochs(stub, 5)

        # dead epoch gone, previous epoch still available to the straggler
        keys = [
            k if isinstance(k, str) else k.decode()
            for k in client.keys("torchft/")
        ]
        assert not any(k.startswith("torchft/3/") for k in keys), keys

        # rank 0 now arrives on epoch 4 and the mesh completes
        c0 = CollectivesTcp(timeout=timedelta(seconds=20), hostname="localhost")
        try:
            c0.configure(prefix4, 0, 2)
            out = c0.allreduce(
                [np.full(8, 1.0, dtype=np.float32)], ReduceOp.SUM
            ).wait(timedelta(seconds=10))
            t.join(timeout=20)
            assert not t.is_alive(), "straggler wedged"
            assert results["straggler"] == 3.0, results
            assert float(out[0][0]) == 3.0
        finally:
            c0.shutdown()
    finally:
        client.close()
        store.shutdown()
