// Seeded bug for the native concurrency lint: a non-seq_cst atomic op
// with no `// relaxed-ok:` / `// release-order:` reason annotation
// (bump_bad), next to a correctly annotated one (bump_ok) that must NOT
// be flagged.
#pragma once
#include <atomic>

struct Counters {
  std::atomic<unsigned long> hits{0};
  std::atomic<unsigned long> misses{0};
};

inline void bump_bad(Counters& c) {
  c.hits.fetch_add(1, std::memory_order_relaxed);
}

inline void bump_ok(Counters& c) {
  // relaxed-ok: monotonic stat counter, no ordering needed
  c.misses.fetch_add(1, std::memory_order_relaxed);
}
