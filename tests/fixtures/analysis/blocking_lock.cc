// Seeded bug for the native concurrency lint: a blocking syscall under a
// held guard (reply_locked — the PR 9 serve_one reply-under-mutex class)
// plus a bare cv.wait with no predicate outside any loop. The ok_* twins
// must NOT be flagged: the send happens after the guard scope closes,
// and the predicate-overload wait self-checks.
#include <condition_variable>
#include <mutex>
#include <sys/socket.h>

class Server {
 public:
  void reply_locked(int fd, const char* buf, int n) {
    std::lock_guard<std::mutex> g(mu_);
    pending_--;
    send(fd, buf, n, 0);
  }

  void reply_ok(int fd, const char* buf, int n) {
    {
      std::lock_guard<std::mutex> g(mu_);
      pending_--;
    }
    send(fd, buf, n, 0);
  }

  void wait_bad() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk);
  }

  void wait_ok() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return pending_ == 0; });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  int pending_ = 0;
};
