# Seeded bug for the makefile-hdrs-drift rule: the header list is
# missing newthing.h (its edits would silently ship a stale .so — the
# tsdb.h/profiler.h incident class) and still lists gone.h, which no
# longer exists.
CXX ?= g++
SRCS := core.cc
HDRS := wire.h rpc.h \
        gone.h

all: lib.so

lib.so: $(SRCS) $(HDRS)
	$(CXX) -shared -o $@ $(SRCS)
