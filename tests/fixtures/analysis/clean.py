"""Fixture that every concurrency rule must pass: the disciplined twin
of the seeded-bug files (single lock order, no blocking under locks,
annotated cross-thread state, predicate-looped waits, named daemon
thread)."""
import threading
import time


class Clean:
    def __init__(self):
        self._cond = threading.Condition()
        self._n = 0  # guarded-by: _cond
        # unguarded-ok: handoff — written only before the worker starts
        self._cfg = None
        self.ready = False
        self._t = threading.Thread(
            target=self._loop, name="clean_loop", daemon=True
        )

    def _loop(self):
        with self._cond:
            self._n += 1
            while not self.ready:
                self._cond.wait()

    def bump(self):
        with self._cond:
            self._n += 1

    def idle(self):
        time.sleep(0.01)
