"""Seeded fixture: anonymous thread (thread-unnamed)."""
import threading


def spawn():
    t = threading.Thread(target=print, daemon=True)
    t.start()
    return t
