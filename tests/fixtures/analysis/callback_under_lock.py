"""Seeded fixture: future resolved while holding a lock."""
import threading


class Resolver:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []

    def fail_all(self, exc):
        with self._lock:
            for fut in self._pending:
                fut.set_exception(exc)
            self._pending.clear()
