"""Seeded fixture: blocking call while holding a lock."""
import threading
import time


class Blocky:
    def __init__(self):
        self._lock = threading.Lock()
        self.last = 0.0

    def slow(self):
        with self._lock:
            time.sleep(0.5)
            self.last = time.monotonic()
