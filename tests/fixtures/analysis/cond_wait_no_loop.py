"""Seeded fixture: Condition.wait outside a predicate loop."""
import threading


class NoLoop:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False

    def wait_once(self):
        with self._cond:
            if not self.ready:
                self._cond.wait()
            return self.ready
