"""Seeded fixture: guarded-by declared, one write site not under the lock."""
import threading


class BadGuard:
    def __init__(self):
        self._mu = threading.Lock()
        self._n = 0  # guarded-by: _mu
        self._t = threading.Thread(
            target=self._loop, name="fixture_loop", daemon=True
        )

    def _loop(self):
        with self._mu:
            self._n += 1

    def bump(self):
        self._n += 1
