// Seeded fixture: C++ side of a wire-constant mismatch. STR exists here
// but not in wire_mismatch_py.txt; F64's value disagrees.
#pragma once

namespace fixture {

enum class Type : uint8_t {
  NIL = 0,
  I64 = 1,
  F64 = 2,
  STR = 3,
};

}  // namespace fixture
