"""Seeded fixture: cross-thread write with no guarded-by annotation."""
import threading


class Unguarded:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(
            target=self._loop, name="fixture_loop", daemon=True
        )

    def _loop(self):
        self._n += 1

    def bump(self):
        self._n += 1
