// Clean twin for the native concurrency lint: consistent lock order,
// blocking work outside guards, predicate-loop cv waits, annotated
// atomics. Must produce ZERO findings.
#include <atomic>
#include <condition_variable>
#include <mutex>
#include <sys/socket.h>

class Worker {
 public:
  void submit() {
    std::lock_guard<std::mutex> g(mu_a_);
    std::lock_guard<std::mutex> g2(mu_b_);
    jobs_++;
  }

  void finish() {
    std::lock_guard<std::mutex> g(mu_a_);
    std::lock_guard<std::mutex> g2(mu_b_);
    jobs_--;
    cv_.notify_all();
  }

  void drain_then_send(int fd, const char* buf, int n) {
    {
      std::unique_lock<std::mutex> lk(mu_b_);
      while (jobs_ > 0) {
        cv_.wait(lk);
      }
    }
    send(fd, buf, n, 0);
  }

  unsigned long ticks() const {
    // relaxed-ok: monotonic stat counter, no ordering needed
    return ticks_.load(std::memory_order_relaxed);
  }

 private:
  std::mutex mu_a_;
  std::mutex mu_b_;
  std::condition_variable cv_;
  std::atomic<unsigned long> ticks_{0};
  int jobs_ = 0;
};
