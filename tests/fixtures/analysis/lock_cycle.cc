// Seeded bug for the native concurrency lint: a lock-order inversion.
// thread A: push() takes mu_a_ then (via refill) mu_b_;
// thread B: drain() takes mu_b_ then mu_a_ — opposing order, deadlock.
#include <mutex>

class Queue {
 public:
  void push() {
    std::lock_guard<std::mutex> g(mu_a_);
    refill();
  }

  void refill() {
    std::lock_guard<std::mutex> g(mu_b_);
    depth_++;
  }

  void drain() {
    std::lock_guard<std::mutex> g(mu_b_);
    std::lock_guard<std::mutex> g2(mu_a_);
    depth_--;
  }

 private:
  std::mutex mu_a_;
  std::mutex mu_b_;
  int depth_ = 0;
};
