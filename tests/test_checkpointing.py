"""Checkpoint transport tests.

Ports the reference's transport coverage (http_transport_test.py,
pg_transport_test.py, rwlock_test.py, transport_test.py shared harness) to
JAX pytree state dicts.
"""

import io
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.checkpointing import (
    CollectivesTransport,
    HTTPTransport,
    RWLock,
)
from torchft_tpu.checkpointing.serialization import (
    dumps_state,
    flatten_state,
    loads_state,
    unflatten_state,
)
from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.store import StoreServer


def assert_state_equal(a, b):
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        if isinstance(x, (np.ndarray,)) or hasattr(x, "dtype"):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        else:
            assert x == y


STATE = {
    "model": {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.ones(4, dtype=np.bfloat16)
        if hasattr(np, "bfloat16")
        else np.ones(4, dtype=np.float16),
    },
    "opt": {"lr": 0.1, "mu": np.zeros((2, 2), dtype=np.float64)},
    "meta": ("strings", 7, None),
}


class TestSerialization:
    def test_roundtrip(self):
        out = loads_state(dumps_state(STATE))
        assert_state_equal(STATE, out)

    def test_jax_arrays(self):
        import jax.numpy as jnp

        state = {"x": jnp.arange(8, dtype=jnp.bfloat16), "y": jnp.float32(3.5)}
        out = loads_state(dumps_state(state))
        np.testing.assert_array_equal(
            np.asarray(state["x"]), np.asarray(out["x"])
        )

    def test_flatten_unflatten(self):
        header, bufs = flatten_state(STATE)
        raw = [np.frombuffer(memoryview(b).cast("B"), dtype=np.uint8) for b in bufs]
        assert_state_equal(STATE, unflatten_state(header, raw))

    def test_to_host_tree_copy_never_aliases(self):
        from torchft_tpu.checkpointing.serialization import to_host_tree

        params = {"w": np.arange(6, dtype=np.float32)}
        backup = to_host_tree(params, copy=True)
        assert not np.shares_memory(backup["w"], params["w"])
        params["w"][...] = -1  # in-place inner update
        np.testing.assert_array_equal(
            backup["w"], np.arange(6, dtype=np.float32)
        )
        # without copy, a contiguous numpy leaf passes through unchanged
        assert to_host_tree(params)["w"] is params["w"]


class TestRWLock:
    def test_readers_shared_writer_exclusive(self):
        lock = RWLock(timeout=1.0)
        lock.r_acquire()
        lock.r_acquire()  # second reader ok
        with pytest.raises(TimeoutError):
            lock.w_acquire()
        lock.r_release()
        lock.r_release()
        with lock.write_lock():
            with pytest.raises(TimeoutError):
                lock.r_acquire()
        lock.r_acquire()
        lock.r_release()

    def test_pending_writer_blocks_new_readers(self):
        lock = RWLock(timeout=5.0)
        lock.r_acquire()
        t = threading.Thread(target=lock.w_acquire)  # parks behind the reader
        t.start()
        time.sleep(0.1)
        # a new reader must queue behind the pending writer, not starve it
        got_read = threading.Event()

        def late_reader():
            lock.r_acquire()
            got_read.set()
            lock.r_release()

        r = threading.Thread(target=late_reader)
        r.start()
        assert not got_read.wait(0.3)
        lock.r_release()  # writer wins first...
        t.join(timeout=5)
        assert lock.w_locked()
        lock.w_release()  # ...then the late reader proceeds
        assert got_read.wait(5)
        r.join(timeout=5)

    def test_writer_timeout_wakes_blocked_readers(self):
        # a writer that times out must notify readers parked on
        # `_want_write == 0`, or they stall until their own timeout
        lock = RWLock(timeout=0.3)
        lock.r_acquire()  # keeps the writer from ever acquiring
        got_read = threading.Event()

        def late_reader():
            lock.r_acquire()
            got_read.set()
            lock.r_release()

        writer_done = threading.Event()

        def failing_writer():
            with pytest.raises(TimeoutError):
                lock.w_acquire()
            writer_done.set()

        w = threading.Thread(target=failing_writer)
        w.start()
        time.sleep(0.05)  # writer is pending; reader queues behind it
        r = threading.Thread(target=late_reader)
        r.start()
        assert writer_done.wait(2)
        # reader must wake promptly after the writer's timeout, well before
        # its own 0.3s deadline from this instant
        assert got_read.wait(0.2)
        w.join(timeout=2)
        r.join(timeout=2)
        lock.r_release()


@pytest.mark.parametrize("num_chunks", [0, 3])
def test_http_transport_roundtrip(num_chunks):
    send = HTTPTransport(timeout=timedelta(seconds=10), num_chunks=num_chunks)
    recv = HTTPTransport(timeout=timedelta(seconds=10), num_chunks=num_chunks)
    try:
        send.send_checkpoint([1], step=5, state_dict=STATE, timeout=timedelta(seconds=10))
        out = recv.recv_checkpoint(
            src_rank=0, metadata=send.metadata(), step=5, timeout=timedelta(seconds=10)
        )
        assert_state_equal(STATE, out)
        # wrong step is rejected
        with pytest.raises(Exception):
            recv.recv_checkpoint(
                src_rank=0,
                metadata=send.metadata(),
                step=99,
                timeout=timedelta(seconds=5),
            )
    finally:
        send.shutdown()
        recv.shutdown()


def test_http_transport_blocks_until_staged():
    send = HTTPTransport(timeout=timedelta(seconds=10))
    recv = HTTPTransport(timeout=timedelta(seconds=10))
    try:
        results = {}

        def fetch():
            results["state"] = recv.recv_checkpoint(
                src_rank=0,
                metadata=send.metadata(),
                step=1,
                timeout=timedelta(seconds=10),
            )

        t = threading.Thread(target=fetch)
        t.start()
        time.sleep(0.3)
        assert "state" not in results  # GET is parked on the write lock
        send.send_checkpoint([1], step=1, state_dict=STATE, timeout=timedelta(seconds=10))
        t.join(timeout=10)
        assert_state_equal(STATE, results["state"])

        # after disallow, subsequent fetches park until the next staging
        send.disallow_checkpoint()
        with pytest.raises(Exception):
            recv2 = HTTPTransport(timeout=timedelta(milliseconds=300))
            try:
                recv2.recv_checkpoint(
                    src_rank=0,
                    metadata=send.metadata(),
                    step=1,
                    timeout=timedelta(milliseconds=500),
                )
            finally:
                recv2.shutdown()
    finally:
        send.shutdown()
        recv.shutdown()


def test_collectives_transport_roundtrip():
    store = StoreServer()
    try:
        colls = [CollectivesTcp(timeout=timedelta(seconds=10)) for _ in range(2)]
        with ThreadPoolExecutor(max_workers=2) as pool:
            list(
                pool.map(
                    lambda i: colls[i].configure(store.address(), i, 2), range(2)
                )
            )
        transports = [
            CollectivesTransport(c, timeout=timedelta(seconds=10)) for c in colls
        ]

        def send():
            transports[0].send_checkpoint(
                [1], step=3, state_dict=STATE, timeout=timedelta(seconds=10)
            )

        def recv():
            return transports[1].recv_checkpoint(
                src_rank=0, metadata="<collectives>", step=3, timeout=timedelta(seconds=10)
            )

        with ThreadPoolExecutor(max_workers=2) as pool:
            fs = pool.submit(send)
            fr = pool.submit(recv)
            fs.result(timeout=20)
            out = fr.result(timeout=20)
        assert_state_equal(STATE, out)
        for c in colls:
            c.shutdown()
    finally:
        store.shutdown()


def test_collectives_transport_parallel_fanout_windowed():
    """Round-3: ≤3 in-flight buffers per destination, destinations in
    parallel (the reference's pg_transport.py:171-198 pipeline). A
    many-buffer state dict to TWO healing replicas at once must land
    intact on both."""
    store = StoreServer()
    state = {f"leaf{i}": np.full(4096, float(i), dtype=np.float32) for i in range(24)}
    try:
        colls = [CollectivesTcp(timeout=timedelta(seconds=20)) for _ in range(3)]
        with ThreadPoolExecutor(max_workers=3) as pool:
            list(
                pool.map(
                    lambda i: colls[i].configure(store.address(), i, 3), range(3)
                )
            )
        transports = [
            CollectivesTransport(c, timeout=timedelta(seconds=20)) for c in colls
        ]

        with ThreadPoolExecutor(max_workers=3) as pool:
            fs = pool.submit(
                transports[0].send_checkpoint,
                [1, 2],
                5,
                state,
                timedelta(seconds=20),
            )
            frs = [
                pool.submit(
                    transports[r].recv_checkpoint,
                    0,
                    "<collectives>",
                    5,
                    timedelta(seconds=20),
                )
                for r in (1, 2)
            ]
            fs.result(timeout=30)
            outs = [fr.result(timeout=30) for fr in frs]
        for out in outs:
            assert_state_equal(state, out)
        for c in colls:
            c.shutdown()
    finally:
        store.shutdown()


# ---------------------------------------------------------------------------
# DiskCheckpointer (periodic user-owned checkpoints; reference workflow
# train_ddp.py:141-148 + manager.py:83-85 docs)
# ---------------------------------------------------------------------------


class _ManagerStub:
    def __init__(self) -> None:
        self.step = 0
        self.batches = 0

    def current_step(self) -> int:
        return self.step

    def state_dict(self):
        return {"step": self.step, "batches_committed": self.batches}

    def load_state_dict(self, s) -> None:
        self.step = s["step"]
        self.batches = s["batches_committed"]


def test_disk_checkpointer_cadence_retention_restore(tmp_path):
    from torchft_tpu.checkpointing.disk import DiskCheckpointer

    mgr = _ManagerStub()
    state = {"w": np.arange(4, dtype=np.float32)}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=lambda: dict(state),
        load_state_dict=lambda s: state.update(s),
        every=2,
        keep=2,
        tag="g0",
    )
    saved = []
    for step in range(1, 9):
        mgr.step = step
        mgr.batches = step * 2
        state["w"] = state["w"] + 1.0
        if ck.maybe_save():
            saved.append(step)
    assert saved == [2, 4, 6, 8]  # cadence honored, no re-save on stall
    mgr.step = 8
    assert ck.maybe_save() is None  # no progress since last save
    names = sorted(p.name for p in tmp_path.glob("g0_step*.ckpt"))
    assert names == ["g0_step6.ckpt", "g0_step8.ckpt"]  # keep=2 pruned

    # total failure: fresh process state, restore latest
    mgr2 = _ManagerStub()
    state2 = {}
    ck2 = DiskCheckpointer(
        str(tmp_path),
        mgr2,
        state_dict=lambda: dict(state2),
        load_state_dict=lambda s: state2.update(s),
        every=2,
        tag="g0",
    )
    assert ck2.restore() is True
    assert mgr2.step == 8 and mgr2.batches == 16
    np.testing.assert_array_equal(state2["w"], np.arange(4, dtype=np.float32) + 8)


def test_disk_checkpointer_non_writer_and_empty(tmp_path):
    from torchft_tpu.checkpointing.disk import DiskCheckpointer

    mgr = _ManagerStub()
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=dict,
        load_state_dict=lambda s: None,
        tag="g1",
        is_writer=False,
    )
    mgr.step = 5
    assert ck.maybe_save() is None  # readers never write
    assert ck.restore() is False  # nothing to restore


def test_disk_checkpointer_sharded_leaves(tmp_path):
    """A sharded param tree round-trips per shard: the restored leaves are
    ShardedArray placeholders rebuilt on the local mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchft_tpu.checkpointing.disk import DiskCheckpointer
    from torchft_tpu.checkpointing.serialization import from_transfer_tree
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(tp=2), devices=jax.devices()[:2])
    w = jax.device_put(
        jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
        NamedSharding(mesh, P(None, "tp")),
    )
    mgr = _ManagerStub()
    holder = {"w": w}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=lambda: dict(holder),
        load_state_dict=lambda s: holder.update(
            from_transfer_tree(s, mesh)
        ),
        every=1,
        tag="g0",
    )
    mgr.step = 1
    assert ck.maybe_save()
    holder.clear()
    assert ck.restore()
    np.testing.assert_array_equal(np.asarray(holder["w"]), np.asarray(w))
    assert holder["w"].sharding.spec == P(None, "tp")


def test_disk_checkpointer_async_save_tear_free(tmp_path):
    """async_save: the snapshot is captured at maybe_save() time — numpy
    leaves mutated immediately afterward must not leak into the file."""
    import os

    from torchft_tpu.checkpointing.disk import DiskCheckpointer

    mgr = _ManagerStub()
    state = {"w": np.full(1 << 16, 1.0, dtype=np.float32)}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=lambda: dict(state),
        load_state_dict=lambda s: state.update(s),
        every=1,
        tag="g0",
        async_save=True,
    )
    mgr.step = 1
    path = ck.maybe_save()
    assert path is not None
    state["w"][...] = 999.0  # in-place mutation racing the writer
    ck.flush()
    assert os.path.exists(path)

    mgr2 = _ManagerStub()
    got = {}
    ck2 = DiskCheckpointer(
        str(tmp_path),
        mgr2,
        state_dict=dict,
        load_state_dict=lambda s: got.update(s),
        tag="g0",
    )
    assert ck2.restore()
    np.testing.assert_array_equal(got["w"], 1.0)  # snapshot-time value
    assert mgr2.step == 1


def test_disk_checkpointer_per_process_merge(tmp_path):
    """Multi-host sharded checkpoints (round-2 advisor finding): one writer
    per group cannot serialize a cross-process-sharded leaf, so every
    process writes a ``procIofN`` shard file and restore() merges the set.
    Two simulated hosts each hold half the shards of an ('x',)-sharded
    (8,4) leaf; restore must pool them so the full array is recoverable."""
    from torchft_tpu.checkpointing.disk import DiskCheckpointer, _NAME
    from torchft_tpu.checkpointing.serialization import ShardedArray, save_state

    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    mesh_desc = (("x",), (4,))
    spec = ("x",)

    def half(lo_rows):
        shards = [
            (((r, r + 2), (0, 4)), full[r : r + 2]) for r in lo_rows
        ]
        return ShardedArray(np.dtype(np.float32), (8, 4), mesh_desc, spec, shards)

    # hand-write the two per-process files (the write path on a real
    # multi-host deployment produces exactly this layout via _target_path)
    for pidx, rows in ((0, (0, 2)), (1, (4, 6))):
        torchft = {"step": 5, "batches_committed": 10}
        path = tmp_path / f"g0_step5.proc{pidx}of2.ckpt"
        with open(path, "wb") as f:
            save_state({"torchft": torchft, "user": {"w": half(rows)}}, f)
        assert _NAME.match(path.name)

    mgr = _ManagerStub()
    got = {}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=dict,
        load_state_dict=lambda s: got.update(s),
        tag="g0",
    )
    assert ck.restore() is True
    assert mgr.step == 5
    merged = got["w"]
    assert isinstance(merged, ShardedArray)
    assert len(merged.shards) == 4  # both halves pooled
    np.testing.assert_array_equal(merged.full(), full)


def test_disk_checkpointer_incomplete_proc_set_not_restorable(tmp_path):
    """A per-process set missing a writer (host died mid-save) must not be
    offered as restorable — restore falls back to an older complete step."""
    from torchft_tpu.checkpointing.disk import DiskCheckpointer
    from torchft_tpu.checkpointing.serialization import save_state

    # complete dense checkpoint at step 3
    with open(tmp_path / "g0_step3.ckpt", "wb") as f:
        save_state(
            {
                "torchft": {"step": 3, "batches_committed": 6},
                "user": {"w": np.ones(2, np.float32)},
            },
            f,
        )
    # step 5: only proc0of2 present — incomplete
    with open(tmp_path / "g0_step5.proc0of2.ckpt", "wb") as f:
        save_state(
            {
                "torchft": {"step": 5, "batches_committed": 10},
                "user": {"w": np.zeros(2, np.float32)},
            },
            f,
        )
    mgr = _ManagerStub()
    got = {}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=dict,
        load_state_dict=lambda s: got.update(s),
        tag="g0",
    )
    assert ck.restore() is True
    assert mgr.step == 3  # fell back to the complete step
    np.testing.assert_array_equal(got["w"], 1.0)


def test_disk_checkpointer_needs_per_process_detection():
    """Single-process (even with an 8-device mesh) state is fully
    addressable — the dense single-writer layout stays in effect."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchft_tpu.checkpointing.disk import _needs_per_process

    devs = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devs), ("x",))
    arr = jax.device_put(
        jnp.arange(8, dtype=jnp.float32), NamedSharding(mesh, P("x"))
    )
    assert arr.is_fully_addressable
    assert _needs_per_process({"w": arr}) is False
    assert _needs_per_process({"w": np.ones(3)}) is False


def test_disk_dense_vs_proc_set_same_step_prefers_newer(tmp_path):
    """Elastic resize can leave BOTH a dense file and a complete procIofN
    set at the same step; restore must take the newer write, never merge
    the stale one (round-3 review finding on _existing())."""
    import os

    from torchft_tpu.checkpointing.disk import DiskCheckpointer
    from torchft_tpu.checkpointing.serialization import save_state

    mgr = _ManagerStub()
    mgr.step = 5
    state = {"w": np.zeros(4, dtype=np.float32)}
    ck = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=lambda: dict(state),
        load_state_dict=lambda s: state.update(s),
        tag="g0",
    )

    def write(path, w):
        with open(path, "wb") as f:
            save_state(
                {"torchft": mgr.state_dict(), "user": {"w": w}}, f
            )

    stale = np.full(4, 1.0, dtype=np.float32)
    fresh = np.full(4, 2.0, dtype=np.float32)

    # older: a complete 2-process set; newer: a dense re-save (shrink to 1)
    write(ck._proc_path(5, 0, 2), stale)
    write(ck._proc_path(5, 1, 2), stale)
    stale_mtime = os.path.getmtime(ck._proc_path(5, 0, 2))
    write(ck._path(5), fresh)
    # explicit times: guarantees strictly-newer even on coarse-granularity
    # filesystems where sleep+now would truncate to the same second
    os.utime(ck._path(5), (stale_mtime + 2, stale_mtime + 2))

    assert ck.latest() == ck._path(5)
    assert ck.restore()
    np.testing.assert_array_equal(state["w"], fresh)

    # the reverse: dense older, proc set newer -> proc set wins
    for p in [ck._path(5), ck._proc_path(5, 0, 2), ck._proc_path(5, 1, 2)]:
        os.remove(p)
    write(ck._path(5), stale)
    stale_mtime = os.path.getmtime(ck._path(5))
    write(ck._proc_path(5, 0, 1), fresh)  # 1-process "set"
    os.utime(ck._proc_path(5, 0, 1), (stale_mtime + 2, stale_mtime + 2))
    assert ck.latest() == ck._proc_path(5, 0, 1)


def test_disk_write_generation_beats_mtime(tmp_path):
    """Deterministic dense-vs-procset arbitration (round-3 advisor low):
    a later incarnation's write wins via its higher write generation even
    when filesystem mtimes tie or INVERT (1 s granularity, clock skew)."""
    import os

    from torchft_tpu.checkpointing.disk import DiskCheckpointer
    from torchft_tpu.checkpointing.serialization import save_state

    mgr = _ManagerStub()
    mgr.step = 5

    def write(path, w):
        with open(path, "wb") as f:
            save_state({"torchft": mgr.state_dict(), "user": {"w": w}}, f)

    stale = np.full(4, 1.0, dtype=np.float32)
    fresh = np.full(4, 2.0, dtype=np.float32)

    # incarnation 1 (fresh dir -> gen 0, legacy names): 2-process set
    ck1 = DiskCheckpointer(
        str(tmp_path), mgr, state_dict=dict, load_state_dict=lambda s: None, tag="g0"
    )
    assert ck1._gen == 0
    write(ck1._proc_path(5, 0, 2), stale)
    write(ck1._proc_path(5, 1, 2), stale)

    # incarnation 2 (resized to 1 process): scans -> gen 1
    state2 = {}
    ck2 = DiskCheckpointer(
        str(tmp_path),
        mgr,
        state_dict=dict,
        load_state_dict=lambda s: state2.update(s),
        tag="g0",
    )
    assert ck2._gen == 1
    write(ck2._path(5), fresh)
    # adversarial: make the NEWER write look mtime-OLDER; gen must win
    old = os.path.getmtime(ck1._proc_path(5, 0, 2)) - 10
    os.utime(ck2._path(5), (old, old))
    assert ck2.latest() == ck2._path(5)
    assert ck2.restore()
    np.testing.assert_array_equal(state2["w"], fresh)

    # a third incarnation keeps climbing
    ck3 = DiskCheckpointer(
        str(tmp_path), mgr, state_dict=dict, load_state_dict=lambda s: None, tag="g0"
    )
    assert ck3._gen == 2


def test_disk_prune_removes_superseded_generations(tmp_path):
    """A crash-restart loop re-saving around the same step must not leak
    one full checkpoint per incarnation: _prune deletes same-step files of
    strictly lower generation than the arbitration winner."""
    from torchft_tpu.checkpointing.disk import DiskCheckpointer

    state = {"w": np.zeros(2, dtype=np.float32)}
    names = lambda: sorted(  # noqa: E731
        p.name for p in tmp_path.iterdir() if p.suffix == ".ckpt"
    )
    for incarnation in range(3):
        mgr = _ManagerStub()
        ck = DiskCheckpointer(
            str(tmp_path),
            mgr,
            state_dict=lambda: dict(state),
            load_state_dict=lambda s: state.update(s),
            every=1,
            keep=3,
            tag="g0",
        )
        assert ck._gen == incarnation
        ck.restore()
        mgr.step = 5  # dies near the same step every time
        ck.save()
    # only the newest generation's file survives at step 5
    assert names() == ["g0_step5.g2.ckpt"]
