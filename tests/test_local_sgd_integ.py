"""LocalSGD / DiLoCo integration tests (local_sgd_integ_test.py analogue).

Same Runner harness as test_integration.py: real lighthouse + managers,
replica groups as threads, recovery via HTTP transport. Asserts model (and
DiLoCo outer-optimizer) state equality across groups after syncs.
"""

from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict

import numpy as np
import optax
import pytest

import jax

from tests.test_integration import FailureInjector, Runner, _init_params, _loss_fn
from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.manager import Manager


def local_sgd_train_loop(
    rank: int, store_addr: str, runner: Runner, total_syncs: int = 2
) -> Dict[str, Any]:
    import optax

    mode = runner.train_loop_args.get("mode", "local_sgd")
    sync_every = 3

    holder = {}

    def load_state(sd):
        holder["params"] = sd["params"]
        holder["opt_state"] = sd["opt_state"]

    def save_state():
        return {"params": holder["params"], "opt_state": holder["opt_state"]}

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=10)),
        load_state_dict=load_state,
        state_dict=save_state,
        min_replica_size=2,
        replica_id=str(runner.replica_id),
        store_addr=store_addr,
        rank=rank,
        world_size=runner.world_size,
        lighthouse_addr=runner.lighthouse_address,
        timeout=timedelta(seconds=10),
        use_async_quorum=False,  # DiLoCo requires sync quorum
    )
    try:
        tx = optax.sgd(0.05)
        holder["params"] = _init_params()
        holder["opt_state"] = tx.init(holder["params"])
        grad_fn = jax.jit(jax.grad(_loss_fn))
        apply_fn = jax.jit(
            lambda p, o, g: (
                lambda u: (optax.apply_updates(p, u[0]), u[1])
            )(tx.update(g, o, p))
        )

        if mode == "local_sgd":
            wrapper = LocalSGD(manager, sync_every=sync_every)
        else:
            wrapper = DiLoCo(
                manager,
                outer_tx=optax.sgd(0.7, momentum=0.9, nesterov=True),
                sync_every=sync_every,
            )
        wrapper.save(holder["params"])

        # live recovery must carry the wrapper's backup/outer state along
        # with the raw params, or a rejoiner syncs from a stale snapshot
        def load_state_full(sd):
            load_state(sd)
            wrapper.load_state_dict(sd["wrapper"])

        def save_state_full():
            sd = save_state()
            sd["wrapper"] = wrapper.state_dict()
            return sd

        manager.set_state_dict_fns(load_state_full, save_state_full)

        data_rng = np.random.default_rng(2000 + runner.replica_id * 31 + rank)
        while manager.current_step() < total_syncs:
            x = data_rng.standard_normal((8, 3)).astype(np.float32)
            y = data_rng.standard_normal((8, 4)).astype(np.float32)
            grads = grad_fn(holder["params"], x, y)
            holder["params"], holder["opt_state"] = apply_fn(
                holder["params"], holder["opt_state"], grads
            )
            holder["params"] = wrapper.step(holder["params"])
            runner.failure_injector.check(rank, manager.current_step())

        out = {
            "params": jax.tree_util.tree_map(np.asarray, holder["params"]),
            "step": manager.current_step(),
        }
        if mode == "diloco":
            out["outer"] = jax.tree_util.tree_map(
                np.asarray, wrapper.outer_state()
            )
        return out
    finally:
        manager.shutdown(wait=False)


class _StubManager:
    """Single-group manager stand-in: allreduce is identity (average of
    one), commit outcome is scripted."""

    _use_async_quorum = False

    def __init__(self, commits):
        self._commits = list(commits)

    def start_quorum(self):
        pass

    def num_participants(self):
        return 1

    def errored(self):
        return None

    def allreduce(self, arr):
        # match the real Manager.allreduce: unwrap to the single array
        return self.allreduce_many([arr]).then(lambda f: f.value()[0])

    def allreduce_many(self, arrays):
        from torchft_tpu.futures import Future

        for arr in arrays:
            np.divide(arr, self.num_participants(), out=arr)
        return Future.completed(arrays)

    def should_commit(self):
        return self._commits.pop(0)


def test_diloco_outer_step_descends_toward_inner_progress():
    """Locks in the paper-sign pseudogradient (backup − local): with plain
    SGD at lr=1 the outer step must land exactly on the averaged inner
    params; a flipped sign would move *away* from the inner progress."""
    start = {"w": np.zeros(4, dtype=np.float32)}
    inner = {"w": np.full(4, 2.0, dtype=np.float32)}

    diloco = DiLoCo(_StubManager([True]), optax.sgd(1.0), sync_every=1)
    diloco.save(start)
    out = diloco.step(inner)
    np.testing.assert_allclose(out["w"], inner["w"], atol=1e-6)

    # lr=0.5 moves exactly halfway from the backup toward the inner params
    diloco = DiLoCo(_StubManager([True]), optax.sgd(0.5), sync_every=1)
    diloco.save(start)
    out = diloco.step(inner)
    np.testing.assert_allclose(out["w"], np.full(4, 1.0), atol=1e-6)


def test_local_sgd_backup_does_not_alias_live_params():
    """Rollback safety: after a committed sync the caller keeps training
    (possibly in place) on the returned params; a later failed commit must
    restore the synced snapshot, not the mutated buffer."""
    lsgd = LocalSGD(_StubManager([True, False, False]), sync_every=1)
    params = {"w": np.full(4, 3.0, dtype=np.float32)}
    lsgd.save(params)
    params["w"][...] = 5.0  # in-place update before the first sync
    synced = lsgd.step(params)  # commit=True: backup snapshots 5.0
    np.testing.assert_array_equal(synced["w"], np.full(4, 5.0))
    synced["w"][...] = 9.0  # in-place inner steps after the sync
    restored = lsgd.step(synced)  # commit=False: roll back to the snapshot
    np.testing.assert_array_equal(restored["w"], np.full(4, 5.0))
    # the restored tree must not alias the snapshot either: mutate it and
    # fail another sync — the snapshot still restores cleanly
    restored["w"][...] = 9.0
    again = lsgd.step(restored)
    np.testing.assert_array_equal(again["w"], np.full(4, 5.0))


@pytest.mark.parametrize("mode", ["local_sgd", "diloco"])
def test_local_sgd_modes(mode):
    _run_modes(mode, [FailureInjector(), FailureInjector()])


@pytest.mark.parametrize("mode", ["local_sgd", "diloco"])
def test_local_sgd_modes_recovery(mode):
    """Kill group 0 after its first committed sync: the restart heals the
    wrapper's backup (and DiLoCo outer state) from the survivor, and its
    stale local params are replaced by the received backup at the next
    sync (LocalSGD._just_healed) — final states must still be identical
    (the reference's local_sgd_integ recovery bar)."""
    _run_modes(mode, [FailureInjector().fail_at(0, 1), FailureInjector()])


def _run_modes(mode, injectors):
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [
                ex.submit(
                    Runner(
                        replica_id=i,
                        lighthouse_address=lighthouse.address(),
                        failure_injector=inj,
                        train_loop=local_sgd_train_loop,
                        train_loop_args={"mode": mode},
                    ).run_replica
                )
                for i, inj in enumerate(injectors)
            ]
            results = [f.result(timeout=120) for f in futs]
    finally:
        lighthouse.shutdown()

    a, b = results[0][0], results[1][0]
    for key in a["params"]:
        np.testing.assert_array_equal(a["params"][key], b["params"][key])
    if mode == "diloco":
        la = jax.tree_util.tree_leaves(a["outer"])
        lb = jax.tree_util.tree_leaves(b["outer"])
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)
