"""Sublinear fleet telemetry (ISSUE 16): delta-encoded piggybacks,
mergeable fleet rollups, and the self-metering plane.

Covers the flatten/unflatten path vocabulary, delta round-trips over
every wire leaf type (float / int / bool / str / bytes / delete) plus
version skew, empty deltas and type-sensitivity, the byte-cap
field-by-field degradation (tier-0 latches survive, deferred fields stay
dirty and ship later), kill/respawn incarnation resync against a live
lighthouse (a new incarnation never inherits the dead chain; the dead
TSDB ring is retained), fleet rollup merge exactness vs a Python mirror
of the native grid-quantile math, /cluster.json cursor pagination +
``?since=``, the Manager-side self-metering counters, and the ``tack``
ack loop through a real ManagerServer quorum.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from datetime import timedelta
from types import SimpleNamespace

import pytest

from torchft_tpu import _native, telemetry
from torchft_tpu.telemetry.fleetdelta import (
    IDX,
    SEP,
    DeltaDecoder,
    DeltaEncoder,
    flatten,
    poll_fleet,
    tier_of,
    unflatten,
)


@pytest.fixture(autouse=True)
def _delta_on(monkeypatch):
    # this file tests the delta plane — pin the default-on knob so an
    # outer TORCHFT_TELEMETRY_DELTA=0 (e.g. a legacy-path suite sweep)
    # can't silently reroute these tests onto the JSON payload
    monkeypatch.setenv("TORCHFT_TELEMETRY_DELTA", "1")


@pytest.fixture
def lighthouse():
    from torchft_tpu.coordination import LighthouseClient, LighthouseServer

    _native.tsdb_reset()
    lh = LighthouseServer(bind="[::]:0", min_replicas=1)
    client = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
    try:
        yield lh, client
    finally:
        client.close()
        lh.shutdown()
        _native.tsdb_reset()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


def _report(step=1, **extra):
    base = {"step": step, "epoch": 1, "stuck": False, "slo_breach": False,
            "local_step_p50_s": 0.1, "last_heal_ts": 0.0}
    base.update(extra)
    return base


# ---------------------------------------------------------------------------
# flatten / unflatten — the path vocabulary under the delta format
# ---------------------------------------------------------------------------


class TestFlatten:
    def test_nested_round_trip_with_lists(self):
        obj = {
            "a": {"b": 1, "c": [1.5, "x", True]},
            "d": "plain",
            "e": [],  # empty list must survive via the length marker
        }
        assert unflatten(flatten(obj)) == obj

    def test_none_leaves_are_skipped(self):
        flat = flatten({"a": None, "b": 2})
        assert list(flat) == ["b"]

    def test_list_paths_use_idx_and_length_markers(self):
        flat = flatten({"l": [7, 8]})
        assert flat["l" + SEP + IDX + "0"] == 7
        assert flat["l" + SEP + IDX + "#"] == 2

    def test_huge_int_degrades_to_float(self):
        flat = flatten({"big": 1 << 80})
        assert isinstance(flat["big"], float)

    def test_foreign_type_degrades_to_str(self):
        # tuples flatten as lists; a truly foreign leaf degrades to
        # str(v) — the legacy json.dumps(default=str) contract
        flat = flatten({"t": complex(1, 2)})
        assert flat["t"] == str(complex(1, 2))

    def test_tiers(self):
        assert tier_of("step") == 0
        assert tier_of("series" + SEP + "flag.slo_breach") == 0
        assert tier_of("summary" + SEP + "steps") == 1
        assert tier_of("series" + SEP + "local_s") == 1
        assert tier_of("anatomy" + SEP + "p50") == 2
        assert tier_of("hist" + SEP + "wall" + SEP + "3") == 2


# ---------------------------------------------------------------------------
# delta round-trips (Python encoder <-> Python decoder oracle)
# ---------------------------------------------------------------------------


class TestDeltaRoundTrip:
    def test_every_leaf_type_round_trips(self):
        enc, dec = DeltaEncoder(), DeltaDecoder()
        r = _report(f=1.25, i=-42, b=True, s="héllo", raw=b"\x00\xffbin")
        out = dec.apply(enc.encode(r))
        assert out["ok"] and out["full"]
        assert dec.state() == r
        # mutate one of each type + delete one key
        r2 = dict(r, f=2.5, i=43, b=False, s="next", raw=b"\x01")
        del r2["last_heal_ts"]
        out = dec.apply(enc.encode(r2))
        assert out["ok"] and not out["full"]
        assert dec.state() == r2
        assert "last_heal_ts" not in dec.flat

    def test_empty_delta_is_tiny_and_changes_nothing(self):
        enc, dec = DeltaEncoder(), DeltaDecoder()
        r = _report(summary={"steps": 5})
        full = enc.encode(r)
        assert dec.apply(full)["ok"]
        blob = enc.encode(r)  # identical report → zero entries
        assert len(blob) < len(full) / 4
        out = dec.apply(blob)
        assert out["ok"] and out["changed"] == []
        assert dec.state() == r

    def test_steady_state_bytes_are_o_changed_not_o_report(self):
        # 200-key state; one field churns → blob stays flat and small
        enc, dec = DeltaEncoder(), DeltaDecoder()
        r = _report(summary={f"c{i}": i for i in range(200)})
        dec.apply(enc.encode(r))
        sizes = []
        for step in range(2, 6):
            r = dict(r, step=step)
            blob = enc.encode(r)
            assert dec.apply(blob)["ok"]
            sizes.append(len(blob))
        assert max(sizes) < 40  # header + one interned I64 entry
        assert len(set(sizes)) == 1  # flat: O(1) steady state

    def test_type_sensitivity_1_vs_1p0_vs_true(self):
        enc, dec = DeltaEncoder(), DeltaDecoder()
        dec.apply(enc.encode(_report(v=1)))
        assert dec.flat["v"] == 1 and type(dec.flat["v"]) is int
        dec.apply(enc.encode(_report(v=1.0)))
        assert type(dec.flat["v"]) is float
        dec.apply(enc.encode(_report(v=True)))
        assert type(dec.flat["v"]) is bool

    def test_version_skew_requests_resync_and_full_recovers(self):
        enc, dec = DeltaEncoder(), DeltaDecoder()
        dec.apply(enc.encode(_report(step=1)))
        enc.encode(_report(step=2))  # lost on the wire → decoder at v1
        out = dec.apply(enc.encode(_report(step=3)))
        assert not out["ok"] and out["resync_wanted"]
        assert dec.state()["step"] == 1  # stale state untouched
        # the receiver's tack round-trips resync back to the encoder
        enc.on_ack({enc.incarnation.hex(): {"ver": dec.version,
                                            "resync": True}})
        out = dec.apply(enc.encode(_report(step=4)))
        assert out["ok"] and out["full"]
        assert dec.state() == _report(step=4)

    def test_fresh_decoder_rejects_delta_from_unknown_incarnation(self):
        enc = DeltaEncoder()
        enc.encode(_report())  # FULL never delivered
        out = DeltaDecoder().apply(enc.encode(_report(step=2)))
        assert out["resync_wanted"] and not out["ok"]

    def test_unacked_window_forces_defensive_full(self):
        enc = DeltaEncoder()
        enc.encode(_report())
        fulls_before = enc.fulls_total
        for step in range(2, 2 + enc.MAX_UNACKED + 2):  # no acks ever
            enc.encode(_report(step=step))
        assert enc.fulls_total > fulls_before

    def test_seeded_multi_round_state_equality(self):
        # deterministic churn over many rounds: decoder state must equal
        # the sender's report after every single apply
        enc, dec = DeltaEncoder(), DeltaDecoder()
        r = _report(summary={}, series={})
        for step in range(1, 30):
            r = dict(r, step=step, stuck=bool(step % 3 == 0))
            r["summary"] = dict(r["summary"], **{f"c{step % 7}": step})
            r["series"] = {"local_s": step * 0.01}
            if step % 5 == 0 and f"c{(step - 1) % 7}" in r["summary"]:
                r["summary"] = dict(r["summary"])
                del r["summary"][f"c{(step - 1) % 7}"]
            if step % 11 == 0:
                enc.on_ack({enc.incarnation.hex(): {"ver": dec.version}})
            assert dec.apply(enc.encode(r))["ok"]
            assert dec.state() == r


# ---------------------------------------------------------------------------
# byte-cap degradation: field-by-field, latches first (satellite)
# ---------------------------------------------------------------------------


class TestTruncation:
    FAT = {f"phase_{i}": {"p50": 0.001 * i, "p99": 0.002 * i, "n": i}
           for i in range(60)}

    def test_tier0_latches_survive_a_tiny_cap(self):
        enc, dec = DeltaEncoder(max_bytes=256), DeltaDecoder()
        r = _report(step=7, stuck=True, anatomy=self.FAT)
        out = dec.apply(enc.encode(r))
        assert out["ok"]
        assert enc.last_truncated > 0  # anatomy was deferred, loudly
        for key in ("step", "epoch", "stuck", "slo_breach",
                    "local_step_p50_s", "last_heal_ts"):
            assert key in dec.flat, key
        assert dec.flat["stuck"] is True

    def test_deferred_fields_ship_on_later_rounds(self):
        enc, dec = DeltaEncoder(max_bytes=256), DeltaDecoder()
        r = _report(anatomy=self.FAT)
        rounds = 0
        while True:
            rounds += 1
            assert rounds < 100
            assert dec.apply(enc.encode(r))["ok"]
            if enc.last_truncated == 0:
                break
        assert rounds > 1  # the cap actually bit
        assert dec.state() == r  # ... yet nothing was lost
        assert enc.truncated_total > 0


# ---------------------------------------------------------------------------
# kill/respawn: new incarnation never inherits the dead chain (satellite)
# ---------------------------------------------------------------------------


class TestRespawnResync:
    def _send(self, client, rid, blob, spans=None):
        payload = {"tdelta": blob}
        if spans:
            payload["spans"] = spans
        client.heartbeat(rid, telemetry_payload=payload)

    def test_respawn_resyncs_and_dead_tsdb_ring_is_retained(self, lighthouse):
        lh, client = lighthouse
        enc1 = DeltaEncoder()
        for step in range(3):
            r = _report(step=step, series={"local_s": 0.1 + step * 0.01})
            self._send(client, "repR", enc1.encode(r))
        snap = _native.tsdb_snapshot()
        old_samples = snap["repR"]["local_s"]["samples"]
        assert [s[1] for s in old_samples] == [0, 1, 2]
        cl = _get_json(lh.address() + "/cluster.json")
        assert cl["replicas"]["repR"]["step"] == 2

        # respawn: a NEW encoder = new random incarnation. Its delta
        # (FULL lost on the wire) must be parked, never applied against
        # the dead chain's dictionary/base.
        enc2 = DeltaEncoder()
        enc2.encode(_report(step=100))  # FULL never delivered
        fleet0 = poll_fleet(lh.address())
        self._send(client, "repR",
                   enc2.encode(_report(step=101,
                                       series={"local_s": 0.5})))
        fleet1 = poll_fleet(lh.address())
        assert (fleet1["telemetry"]["delta_resyncs_total"]
                > fleet0["telemetry"]["delta_resyncs_total"])
        cl = _get_json(lh.address() + "/cluster.json")
        assert cl["replicas"]["repR"]["step"] == 2  # orphan delta dropped

        # the stall-push path: force_full re-bases the new chain
        enc2.force_full()
        self._send(client, "repR",
                   enc2.encode(_report(step=102,
                                       series={"local_s": 0.6})))
        cl = _get_json(lh.address() + "/cluster.json")
        assert cl["replicas"]["repR"]["step"] == 102
        # dead-ring semantics (PR 11): the replica's TSDB ring is keyed
        # by replica id, so the first incarnation's samples persist
        samples = _native.tsdb_snapshot()["repR"]["local_s"]["samples"]
        steps = [s[1] for s in samples]
        assert steps[:3] == [0, 1, 2] and steps[-1] == 102

    def test_legacy_and_delta_replicas_coexist(self, lighthouse):
        lh, client = lighthouse
        client.heartbeat("legacy", telemetry_payload={
            "step": 5, "epoch": 1,
            "summary": json.dumps({"steps": 5}),
        })
        enc = DeltaEncoder()
        self._send(client, "delta",
                   enc.encode(_report(step=9, summary={"steps": 9})))
        cl = _get_json(lh.address() + "/cluster.json")
        assert cl["replicas"]["legacy"]["step"] == 5
        assert cl["replicas"]["delta"]["step"] == 9
        assert cl["replicas"]["delta"]["summary"] == {"steps": 9}


# ---------------------------------------------------------------------------
# fleet rollup merge exactness (satellite)
# ---------------------------------------------------------------------------


def _grid_quantile(counts, q):
    """Python mirror of native/telemetry_delta.h grid_quantile: bucket i
    spans (2^(i-21), 2^(i-20)] s, overflow interpolates to 2x the last
    bound."""
    total = sum(counts)
    if not total:
        return 0.0
    target = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        nxt = acc + c
        if nxt >= target and c:
            frac = (target - acc) / c
            lo = 0.0 if i == 0 else 2.0 ** (i - 21)
            hi = 2.0 ** (i - 20) if i < 27 else 2.0 ** 7
            return lo + (hi - lo) * frac
        acc = nxt
    return 2.0 ** 7


class TestRollupExactness:
    H_A = {"3": 5, "10": 2}
    H_B = {"3": 1, "12": 4, "27": 2}  # incl. the overflow slot

    def _fold(self):
        counts = [0] * 28
        for h in (self.H_A, self.H_B):
            for k, v in h.items():
                counts[int(k)] += v
        return counts

    def test_fleet_fold_is_exact_sum_and_quantiles_match_oracle(
        self, lighthouse
    ):
        lh, client = lighthouse
        for rid, h in (("repA", self.H_A), ("repB", self.H_B)):
            enc = DeltaEncoder()
            client.heartbeat(rid, telemetry_payload={
                "tdelta": enc.encode(_report(hist={"wall": h})),
            })
        fleet = poll_fleet(lh.address())
        counts = self._fold()
        wall = fleet["hist"]["wall"]
        assert wall["count"] == sum(counts)  # fold is exact by construction
        for q, key in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
            want = _grid_quantile(counts, q)
            assert wall[key] == pytest.approx(want, rel=1e-4, abs=1e-6), key

    def test_group_drilldown_is_that_replicas_own_histogram(self, lighthouse):
        lh, client = lighthouse
        for rid, h in (("repA", self.H_A), ("repB", self.H_B)):
            enc = DeltaEncoder()
            client.heartbeat(rid, telemetry_payload={
                "tdelta": enc.encode(_report(hist={"wall": h})),
            })
        fleet = poll_fleet(lh.address(), group="repB")
        assert fleet["group"]["id"] == "repB"
        assert fleet["group"]["hist"]["wall"]["count"] == sum(
            self.H_B.values()
        )

    def test_absolute_bucket_counts_fold_across_delta_rounds(self, lighthouse):
        # hist buckets ride as ABSOLUTE counts: a later delta replaces,
        # never double-counts
        lh, client = lighthouse
        enc = DeltaEncoder()
        client.heartbeat("repA", telemetry_payload={
            "tdelta": enc.encode(_report(step=1, hist={"wall": {"3": 5}})),
        })
        client.heartbeat("repA", telemetry_payload={
            "tdelta": enc.encode(_report(step=2, hist={"wall": {"3": 8}})),
        })
        fleet = poll_fleet(lh.address())
        assert fleet["hist"]["wall"]["count"] == 8


# ---------------------------------------------------------------------------
# /cluster.json cursor pagination + ?since=
# ---------------------------------------------------------------------------


class TestPagination:
    def test_cursor_walk_covers_the_fleet_without_overlap(self, lighthouse):
        lh, client = lighthouse
        ids = [f"rep{c}" for c in "ABCDE"]
        for i, rid in enumerate(ids):
            client.heartbeat(rid, telemetry_payload={"step": i, "epoch": 1})
        seen, pages, cursor = [], 0, ""
        while True:
            pages += 1
            assert pages <= 10
            url = lh.address() + "/cluster.json?limit=2"
            if cursor:
                url += "&cursor=" + cursor
            page = _get_json(url)
            seen.extend(page["replicas"])
            cursor = page.get("next_cursor", "")
            if not cursor:
                break
        assert pages == 3  # 2 + 2 + 1
        assert sorted(seen) == sorted(ids)
        assert len(seen) == len(set(seen))  # no overlap

    def test_full_scrape_keeps_legacy_shape(self, lighthouse):
        lh, client = lighthouse
        client.heartbeat("repA", telemetry_payload={"step": 1, "epoch": 1})
        page = _get_json(lh.address() + "/cluster.json")
        assert "next_cursor" not in page
        assert page["replica_count"] == 1

    def test_since_filters_stale_replicas(self, lighthouse):
        lh, client = lighthouse
        client.heartbeat("old", telemetry_payload={"step": 1, "epoch": 1})
        time.sleep(0.4)
        client.heartbeat("fresh", telemetry_payload={"step": 2, "epoch": 1})
        page = _get_json(lh.address() + "/cluster.json?since=200")
        assert "fresh" in page["replicas"]
        assert "old" not in page["replicas"]
        page = _get_json(lh.address() + "/cluster.json?since=60000")
        assert sorted(page["replicas"]) == ["fresh", "old"]


# ---------------------------------------------------------------------------
# manager-side self-metering (tentpole part 3)
# ---------------------------------------------------------------------------


def _fake_manager():
    from torchft_tpu.manager import Manager

    fake = SimpleNamespace(
        _slo=SimpleNamespace(breached=lambda: False),
        _watchdog=SimpleNamespace(stalled=False),
        _step=3,
        _quorum_id=2,
        _last_heal_ts=0.0,
        _divergence_latched=False,
        _logger=SimpleNamespace(warning=lambda *a, **k: None),
    )
    for name in ("_delta_encoder", "_telemetry_report",
                 "_telemetry_payload_delta", "_telemetry_payload"):
        setattr(fake, name, getattr(Manager, name).__get__(fake))
    return fake


class TestSelfMetering:
    def test_payload_is_a_decodable_delta_and_bytes_are_metered(self):
        fake = _fake_manager()
        before = telemetry.TELEMETRY_BYTES.labels(channel="piggyback").value
        payload = fake._telemetry_payload()
        assert payload is not None and isinstance(payload["tdelta"], bytes)
        after = telemetry.TELEMETRY_BYTES.labels(channel="piggyback").value
        assert after - before == len(payload["tdelta"])
        dec = DeltaDecoder()
        assert dec.apply(payload["tdelta"])["ok"]
        state = dec.state()
        assert state["step"] == 3 and state["epoch"] == 2
        assert "summary" in state and "hist" in state

    def test_encoder_survives_across_steps_with_one_incarnation(self):
        fake = _fake_manager()
        fake._telemetry_payload()
        inc = fake._tdelta_encoder.incarnation
        fake._step = 4
        payload = fake._telemetry_payload()
        assert fake._tdelta_encoder.incarnation is inc
        assert payload["tdelta"][3:11] == inc
        assert not payload["tdelta"][2] & 0x01  # steady state: a delta

    def test_telemetry_is_a_first_class_anatomy_phase(self):
        from torchft_tpu.telemetry.anatomy import PHASES

        assert "telemetry" in PHASES
        fake = _fake_manager()
        fake._telemetry_payload()
        summary = telemetry.LEDGER.summary()
        phases = summary.get("phases", summary)
        assert "telemetry" in str(phases)

    def test_kill_switch_still_wins_over_delta(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_TELEMETRY_PIGGYBACK", "0")
        assert _fake_manager()._telemetry_payload() is None


# ---------------------------------------------------------------------------
# tack ack loop through a real ManagerServer quorum (tentpole part 1)
# ---------------------------------------------------------------------------


class TestTackLoop:
    def test_acks_advance_and_deltas_keep_applying(self):
        from torchft_tpu.coordination import (
            LighthouseServer,
            ManagerClient,
            ManagerServer,
        )

        _native.tsdb_reset()
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        mgr = ManagerServer(
            replica_id="repT", lighthouse_addr=lh.address(),
            hostname="localhost", bind="[::]:0", store_addr="s",
            world_size=1,
        )
        try:
            c = ManagerClient(mgr.address(),
                              connect_timeout=timedelta(seconds=10))
            enc = DeltaEncoder()
            acked = []
            for step in range(3):
                r = _report(step=step, summary={"steps": step})
                res = c._quorum(
                    rank=0, step=step, checkpoint_metadata="m",
                    shrink_only=False, timeout=timedelta(seconds=10),
                    telemetry_payload={"tdelta": enc.encode(r)},
                )
                ack = res.telemetry_ack
                assert ack is not None
                mine = ack[enc.incarnation.hex()]
                assert not mine.get("resync")
                acked.append(mine["ver"])
                enc.on_ack(ack)
            c.close()
            assert acked == sorted(acked) and acked[-1] > acked[0]
            assert enc.acked_version == acked[-1]
            assert enc.fulls_total == 1  # never re-sent full state
            cl = _get_json(lh.address() + "/cluster.json")
            assert cl["replicas"]["repT"]["step"] == 2
            assert cl["replicas"]["repT"]["summary"] == {"steps": 2}
        finally:
            mgr.shutdown()
            lh.shutdown()
            _native.tsdb_reset()
