"""Fleet time machine (ISSUE 11): native time-series store, per-commit
critical-path attribution, and the perf-regression sentinel.

Covers the native tsdb (piggyback ingest → /timeseries.json range
queries, same-step overwrite, kill/respawn ring persistence, fan-out-cap
loud degrade, C-ABI snapshot), the 64 KiB anatomy-digest cap (dropped
loudly, never truncated — satellite), `merge_lathist` overflow-bucket
exactness (satellite), the series builder, the Page-Hinkley detector
(warm-up immunity, spike robustness, floor, latch/clear hysteresis,
barrier exclusion), per-step critical-path attribution + the what-if
estimate, both fleet monitors against a live in-process lighthouse, the
/critical_path.json route, the postmortem --perf window mode, and the
faultinject `after` onset rule.
"""

from __future__ import annotations

import json
import os
import urllib.request
from datetime import timedelta
from types import SimpleNamespace

import pytest

from torchft_tpu import _native, telemetry
from torchft_tpu.telemetry.anatomy import (
    LOG2_BUCKETS,
    lathist_quantile,
    merge_lathist,
)


@pytest.fixture
def lighthouse():
    from torchft_tpu.coordination import LighthouseClient, LighthouseServer

    _native.tsdb_reset()
    lh = LighthouseServer(bind="[::]:0", min_replicas=1)
    client = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
    try:
        yield lh, client
    finally:
        client.close()
        lh.shutdown()
        _native.tsdb_reset()


def _feed(client, rid, step, series, epoch=1, **extra):
    client.heartbeat(
        rid,
        telemetry_payload={
            "step": step, "epoch": epoch, "series": series, **extra,
        },
    )


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


# ---------------------------------------------------------------------------
# native tsdb store + /timeseries.json
# ---------------------------------------------------------------------------


class TestNativeTsdb:
    def test_ingest_snapshot_and_range_query(self, lighthouse):
        lh, client = lighthouse
        for step in range(6):
            _feed(client, "repA", step, {"local_s": 0.1 + step * 0.01})
        snap = _native.tsdb_snapshot()
        samples = snap["repA"]["local_s"]["samples"]
        assert [s[1] for s in samples] == list(range(6))  # step order
        assert samples[0][0] == 1  # epoch travels
        assert abs(samples[3][2] - 0.13) < 1e-9

        ts = _get_json(lh.address() + "/timeseries.json")
        body = ts["replicas"]["repA"]["local_s"]
        assert body["count"] == 6 and body["stride"] == 1
        assert ts["cursor"]["max_step"] == 5
        assert ts["retain"] >= 1

    def test_since_cursor_and_downsampling(self, lighthouse):
        lh, client = lighthouse
        for step in range(10):
            _feed(client, "repA", step, {"local_s": float(step)})
        ts = _get_json(lh.address() + "/timeseries.json?since=3")
        steps = [s[1] for s in ts["replicas"]["repA"]["local_s"]["samples"]]
        assert steps == [4, 5, 6, 7, 8, 9]  # exclusive cursor
        ts = _get_json(
            lh.address() + "/timeseries.json?since=3&max_points=3"
        )
        body = ts["replicas"]["repA"]["local_s"]
        steps = [s[1] for s in body["samples"]]
        assert body["stride"] == 2
        assert steps[-1] == 9, "newest sample must survive downsampling"
        assert len(steps) <= 4
        # an empty window must ECHO the cursor, never regress it — an
        # idle fleet would otherwise reset incremental consumers into
        # refetching the whole retention window
        ts = _get_json(lh.address() + "/timeseries.json?since=9")
        assert ts["cursor"]["max_step"] == 9

    def test_replica_and_series_filters(self, lighthouse):
        lh, client = lighthouse
        _feed(client, "groupA", 1, {"local_s": 0.1, "wall_s": 0.2})
        _feed(client, "groupB", 1, {"local_s": 0.3})
        ts = _get_json(lh.address() + "/timeseries.json?replica=groupB")
        assert list(ts["replicas"]) == ["groupB"]
        ts = _get_json(lh.address() + "/timeseries.json?series=wall")
        assert list(ts["replicas"]["groupA"]) == ["wall_s"]

    def test_same_step_report_overwrites_not_appends(self, lighthouse):
        # reports ride every quorum RPC; a re-quorum within one step must
        # refresh the sample, not burn retention
        lh, client = lighthouse
        _feed(client, "repA", 3, {"local_s": 0.1})
        _feed(client, "repA", 3, {"local_s": 0.5})
        samples = _native.tsdb_snapshot()["repA"]["local_s"]["samples"]
        assert len(samples) == 1
        assert abs(samples[0][2] - 0.5) < 1e-9

    def test_kill_respawn_full_history_served(self, lighthouse):
        # a dead incarnation's ring is RETAINED; the respawn (fresh uuid)
        # gets its own — /timeseries.json serves both (the acceptance's
        # persistence property, at the protocol level)
        lh, client = lighthouse
        for step in range(5):
            _feed(client, "g1-uuid-dead", step, {"local_s": 0.1})
        # "kill": the old incarnation simply stops reporting
        for step in range(3, 9):
            _feed(client, "g1-uuid-respawn", step, {"local_s": 0.2})
        ts = _get_json(lh.address() + "/timeseries.json?replica=g1-uuid")
        rings = ts["replicas"]
        assert set(rings) == {"g1-uuid-dead", "g1-uuid-respawn"}
        assert len(rings["g1-uuid-dead"]["local_s"]["samples"]) == 5
        assert rings["g1-uuid-respawn"]["local_s"]["samples"][-1][1] == 8

    def test_series_fanout_cap_degrades_loudly(self, lighthouse):
        # past TORCHFT_TSDB_MAX_SERIES (default 64) per replica, new
        # series are refused AND counted — never silently absorbed
        lh, client = lighthouse
        series = {f"s{i:03d}": float(i) for i in range(80)}
        _feed(client, "chatty", 1, series)
        ts = _get_json(lh.address() + "/timeseries.json?replica=chatty")
        assert len(ts["replicas"]["chatty"]) <= 64
        assert ts["dropped_series"] > 0
        metrics = urllib.request.urlopen(
            lh.address() + "/metrics", timeout=5
        ).read().decode()
        assert "torchft_tsdb_dropped_series_total" in metrics

    def test_non_numeric_and_stepless_reports_ignored(self, lighthouse):
        lh, client = lighthouse
        _feed(client, "repA", -1, {"local_s": 0.1})  # no step coordinate
        client.heartbeat(
            "repA",
            telemetry_payload={
                "step": 2, "epoch": 1,
                "series": {"ok": 1.0, "bad": "not-a-number"},
            },
        )
        snap = _native.tsdb_snapshot()
        assert "bad" not in snap.get("repA", {})
        assert len(snap["repA"]["ok"]["samples"]) == 1


# ---------------------------------------------------------------------------
# 64 KiB anatomy piggyback cap (satellite): loud degrade, never truncate
# ---------------------------------------------------------------------------


class TestAnatomyOversizeCap:
    def test_lighthouse_drops_and_counts_oversized_digest(self, lighthouse):
        lh, client = lighthouse
        good = json.dumps({"steps": 1})
        client.heartbeat(
            "repA", telemetry_payload={"step": 1, "anatomy": good}
        )
        oversized = "{" + "x" * (1 << 16) + "}"
        client.heartbeat(
            "repA", telemetry_payload={"step": 2, "anatomy": oversized}
        )
        cluster = _get_json(lh.address() + "/cluster.json")
        rec = cluster["replicas"]["repA"]
        # dropped, not truncated — and the previously-stored digest is
        # cleared too (a stale splice would misattribute the incident)
        assert rec["anatomy"] == {}
        assert rec["anatomy_oversized"] == 1
        metrics = urllib.request.urlopen(
            lh.address() + "/metrics", timeout=5
        ).read().decode()
        assert "torchft_telemetry_oversized_total 1" in metrics

    def test_cluster_json_stays_parseable_after_drop(self, lighthouse):
        # the whole point of dropping instead of truncating: the page
        # must still parse
        lh, client = lighthouse
        client.heartbeat(
            "repA",
            telemetry_payload={
                "step": 1, "anatomy": "{" + "y" * (1 << 16) + "}",
            },
        )
        cluster = _get_json(lh.address() + "/cluster.json")  # parses
        assert "repA" in cluster["replicas"]

    def test_manager_side_guard_replaces_oversized_digest(self, monkeypatch):
        # the replica end of the same cap: _telemetry_payload must send
        # an {"_oversized_bytes": n} marker, not the oversize itself.
        # Legacy full-JSON path only — the delta encoder (ISSUE 16)
        # degrades field-by-field instead (tests/test_fleet_telemetry.py)
        monkeypatch.setenv("TORCHFT_TELEMETRY_DELTA", "0")
        from torchft_tpu.manager import Manager

        big = {"rows": ["z" * 1024] * 100}
        monkeypatch.setattr(telemetry.LEDGER, "summary", lambda: big)
        fake = SimpleNamespace(
            _slo=SimpleNamespace(breached=lambda: False),
            _watchdog=SimpleNamespace(stalled=False),
            _step=3,
            _quorum_id=2,
            _last_heal_ts=0.0,
            _divergence_latched=False,
            _logger=SimpleNamespace(warning=lambda *a, **k: None),
        )
        fake._telemetry_payload_json = Manager._telemetry_payload_json.__get__(
            fake
        )
        payload = Manager._telemetry_payload(fake)
        assert payload is not None
        anatomy = json.loads(payload["anatomy"])
        assert "_oversized_bytes" in anatomy
        assert anatomy["_oversized_bytes"] > (1 << 16)
        assert payload["epoch"] == 2


# ---------------------------------------------------------------------------
# merge_lathist overflow-bucket handling (satellite)
# ---------------------------------------------------------------------------


class TestLathistOverflow:
    N = len(LOG2_BUCKETS) + 1  # 27 finite bounds + the overflow slot

    def _hist(self, finite=0, overflow=0):
        counts = [0] * self.N
        if finite:
            counts[10] = finite
        counts[-1] = overflow
        return {
            "counts": counts,
            "count": finite + overflow,
            "sum_ns": (finite + overflow) * 1000,
        }

    def test_overflow_counts_merge_exactly(self):
        a = {"op": self._hist(finite=3, overflow=2)}
        b = {"op": self._hist(finite=1, overflow=5)}
        merged = merge_lathist(a, b)["op"]
        assert merged["counts"][-1] == 7  # overflow slot is elementwise too
        assert merged["counts"][10] == 4
        assert merged["count"] == 11
        assert merged["sum_ns"] == 11000

    def test_overflow_only_quantile_clamps_to_last_bound(self):
        # all mass past 2^6 s: the interpolated quantile must clamp to
        # the last FINITE bound, never invent a value or divide by zero
        h = self._hist(overflow=10)
        assert lathist_quantile(h, 0.5) == LOG2_BUCKETS[-1]
        assert lathist_quantile(h, 0.99) == LOG2_BUCKETS[-1]

    def test_bucket_count_mismatch_is_loud(self):
        a = {"op": self._hist(finite=1)}
        bad = self._hist(finite=1)
        bad["counts"] = bad["counts"][:-1]  # overflow slot missing
        with pytest.raises(ValueError, match="bucket count mismatch"):
            merge_lathist(a, {"op": bad})

    def test_one_sided_merge_preserves_overflow(self):
        merged = merge_lathist({"op": self._hist(overflow=4)}, {})
        assert merged["op"]["counts"][-1] == 4


# ---------------------------------------------------------------------------
# series builder
# ---------------------------------------------------------------------------


class TestBuildSeries:
    def setup_method(self):
        telemetry.reset()

    def teardown_method(self):
        telemetry.reset()

    def test_series_from_last_row_with_flags(self):
        import time

        from torchft_tpu.telemetry.timeseries import build_series

        telemetry.LEDGER.tick(step=0)
        telemetry.LEDGER.record("compute", 0.08)
        telemetry.LEDGER.record("wire", 0.02)
        time.sleep(0.12)  # real wall between ticks so the row has one
        telemetry.LEDGER.tick(step=1)
        s = build_series(slo_breach=True, divergence=False)
        assert s is not None
        assert s["phase.compute"] == pytest.approx(0.08)
        assert s["phase.wire"] == pytest.approx(0.02)
        assert s["wall_s"] >= 0.12 and s["local_s"] > 0
        # local excludes the barrier phase by construction
        assert s["local_s"] <= s["wall_s"] - 0.02 + 1e-6
        assert s["flag.slo_breach"] == 1.0
        assert s["flag.divergence"] == 0.0

    def test_none_before_first_row_and_when_disabled(self, monkeypatch):
        from torchft_tpu.telemetry.timeseries import build_series

        assert build_series() is None  # no rows yet
        telemetry.LEDGER.tick(step=0)
        telemetry.LEDGER.tick(step=1)
        monkeypatch.setenv("TORCHFT_TSDB_SERIES", "0")
        assert build_series() is None

    def test_fanout_cap_trims_by_priority(self, monkeypatch):
        # a trim must cut diagnostics (flags, lat quantiles) before the
        # series the critical-path/regression planes depend on — an
        # alphabetical trim would cut wall_s FIRST and keep flag.*
        from torchft_tpu.telemetry import timeseries

        telemetry.LEDGER.tick(step=0)
        telemetry.LEDGER.record("compute", 0.01)
        telemetry.LEDGER.tick(step=1)
        monkeypatch.setenv("TORCHFT_TSDB_MAX_SERIES", "4")
        s = timeseries.build_series(slo_breach=True)
        assert s is not None and len(s) == 4
        for essential in ("wall_s", "local_s", "local_p50_s",
                          "phase.compute"):
            assert essential in s, s
        assert not any(k.startswith("flag.") for k in s)


# ---------------------------------------------------------------------------
# Page-Hinkley detector
# ---------------------------------------------------------------------------


class TestPageHinkley:
    def _ph(self, **kw):
        from torchft_tpu.telemetry.regression import PageHinkley

        kw.setdefault("delta", 0.1)
        kw.setdefault("lam", 4.0)
        kw.setdefault("min_n", 8)
        kw.setdefault("k", 4)
        return PageHinkley(**kw)

    def test_level_shift_latches_once_then_clears_on_recovery(self):
        ph = self._ph()
        evs = []
        for x in [0.1] * 12 + [0.25] * 10 + [0.1] * 10:
            r = ph.observe(x)
            if r:
                evs.append(r)
        assert evs == ["latched", "cleared"]
        assert ph.latches == 1
        assert 0.09 < ph.baseline < 0.12  # pre-shift level, frozen

    def test_jit_warmup_does_not_poison_the_baseline(self):
        # the real trace that broke the mean-based first cut: two 30-40x
        # warm-up samples, then steady, then a +150ms shift — the median
        # location must latch the shift anyway
        ph = self._ph()
        xs = [4.0, 0.8] + [0.09] * 10 + [0.25] * 8
        evs = [r for x in xs for r in [ph.observe(x)] if r]
        assert evs == ["latched"]

    def test_single_spike_does_not_latch(self):
        ph = self._ph()
        xs = [0.1] * 20 + [3.0] + [0.1] * 20
        assert [r for x in xs for r in [ph.observe(x)] if r] == []

    def test_steady_jitter_does_not_latch(self):
        import random

        rng = random.Random(42)
        ph = self._ph()
        for _ in range(200):
            assert ph.observe(0.1 + rng.uniform(-0.02, 0.02)) is None

    def test_floor_disarms_micro_series(self):
        # the control-soak lesson: a relative test on a 1ms stream is
        # scheduler noise — 5x shifts under the floor must not latch
        ph = self._ph(floor=0.02)
        xs = [0.001] * 12 + [0.006] * 20
        assert [r for x in xs for r in [ph.observe(x)] if r] == []

    def test_warmup_min_n_blocks_early_latch(self):
        ph = self._ph(min_n=8)
        for x in [0.1, 0.5, 0.1, 0.5, 0.1]:  # wild but < min_n samples
            assert ph.observe(x) is None


class TestRegressionDetector:
    def setup_method(self):
        telemetry.reset()

    def teardown_method(self):
        telemetry.reset()

    def test_latch_names_replica_and_phase_and_emits(self):
        from torchft_tpu.telemetry.regression import RegressionDetector

        det = RegressionDetector(min_n=6, k=3)
        events = []
        for step in range(30):
            v = 0.1 if step < 15 else 0.3
            ev = det.observe("gB", "phase.compute", step, v)
            if ev:
                events.append(ev)
        assert len(events) == 1
        ev = events[0]
        assert ev["event"] == "perf_regression"
        assert ev["replica"] == "gB" and ev["phase"] == "compute"
        assert det.regressed() == [("gB", "phase.compute")]
        kinds = [e["event"] for e in telemetry.EVENTS.recent()]
        assert "perf_regression" in kinds

    def test_barrier_phases_not_watched_by_default(self):
        from torchft_tpu.telemetry.regression import RegressionDetector

        det = RegressionDetector(min_n=4, k=2)
        for step in range(40):
            v = 0.05 if step < 20 else 0.5
            assert det.observe("g", "phase.commit_barrier", step, v) is None
            assert det.observe("g", "phase.wire", step, v) is None

    def test_explicit_listing_overrides_barrier_exclusion(self, monkeypatch):
        from torchft_tpu.telemetry.regression import RegressionDetector

        monkeypatch.setenv(
            "TORCHFT_REGRESSION_SERIES", "phase.commit_barrier"
        )
        det = RegressionDetector(min_n=4, k=2)
        assert det.watched("phase.commit_barrier")
        assert not det.watched("local_s")


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------


class TestCriticalPath:
    def setup_method(self):
        telemetry.reset()

    def teardown_method(self):
        from torchft_tpu.telemetry import critical_path

        critical_path.set_reporter(None)
        telemetry.reset()

    def test_attribute_step_names_gater_and_phase(self):
        from torchft_tpu.telemetry.critical_path import attribute_step

        att = attribute_step({
            "g0": {"wall_s": 0.5, "local_s": 0.2,
                   "phases": {"compute": 0.15, "wire": 0.3}},
            "g1": {"wall_s": 0.5, "local_s": 0.45,
                   "phases": {"compute": 0.4, "wire": 0.02}},
        })
        assert att["gating"] == "g1" and att["phase"] == "compute"
        assert att["blame_s"] == pytest.approx(0.25)
        assert att["whatif_wall_s"] == pytest.approx(0.25)

    def test_blame_never_lands_on_barrier_phases(self):
        from torchft_tpu.telemetry.critical_path import attribute_step

        # the gater's excess sits entirely in its wire wait — blame must
        # fall back to its largest LOCAL phase, not the barrier
        att = attribute_step({
            "g0": {"wall_s": 0.3, "local_s": 0.1,
                   "phases": {"compute": 0.1}},
            "g1": {"wall_s": 0.3, "local_s": 0.25,
                   "phases": {"compute": 0.1, "wire": 0.15}},
        })
        assert att["gating"] == "g1"
        assert "wire" not in att["phase_blame"]

    def test_single_replica_attributes_nothing(self):
        from torchft_tpu.telemetry.critical_path import attribute_step

        assert attribute_step(
            {"g0": {"wall_s": 1.0, "local_s": 0.9, "phases": {}}}
        ) is None

    def test_attributor_accumulates_and_reports_whatif(self):
        from torchft_tpu.telemetry.critical_path import (
            CriticalPathAttributor,
        )

        attr = CriticalPathAttributor()
        for step in range(10):
            attr.observe_step(step, {
                "g0": {"wall_s": 0.4, "local_s": 0.1,
                       "phases": {"compute": 0.1}},
                "g1": {"wall_s": 0.4, "local_s": 0.3,
                       "phases": {"compute": 0.3}},
            })
        rep = attr.report()
        assert rep["steps"] == 10
        assert rep["blame"][0]["replica"] == "g1"
        assert rep["blame"][0]["phase"] == "compute"
        assert rep["blame"][0]["share"] == pytest.approx(1.0)
        # removing g1's excess: 0.4 -> 0.2 per step, rate doubles
        assert rep["whatif_steps_per_sec"] == pytest.approx(
            2 * rep["measured_steps_per_sec"], rel=1e-6
        )
        assert attr.blame_by_replica() == pytest.approx({"g1": 2.0})
        # the counter mirror carries the same totals
        child = telemetry.CRITICAL_PATH_SECONDS.labels(
            replica="g1", phase="compute"
        )
        assert child.value == pytest.approx(2.0)

    def test_critical_path_json_route(self):
        from torchft_tpu.checkpointing.http_transport import HTTPTransport
        from torchft_tpu.telemetry import critical_path

        transport = HTTPTransport(timeout=timedelta(seconds=5))
        try:
            url = f"http://localhost:{transport._port}/critical_path.json"
            body = _get_json(url)
            assert body["monitor"] is False and body["steps"] == 0
            attr = critical_path.CriticalPathAttributor()
            attr.observe_step(1, {
                "g0": {"wall_s": 0.2, "local_s": 0.1, "phases": {}},
                "g1": {"wall_s": 0.2, "local_s": 0.15,
                       "phases": {"compute": 0.15}},
            })
            critical_path.set_reporter(attr)
            body = _get_json(url)
            assert body["monitor"] is True and body["steps"] == 1
            assert body["blame"][0]["replica"] == "g1"
        finally:
            transport.shutdown()


# ---------------------------------------------------------------------------
# fleet monitors against a live lighthouse
# ---------------------------------------------------------------------------


class TestMonitorsEndToEnd:
    def setup_method(self):
        telemetry.reset()

    def teardown_method(self):
        from torchft_tpu.telemetry import critical_path

        critical_path.set_reporter(None)
        telemetry.reset()

    def test_regression_and_critical_path_monitors(self, lighthouse):
        from torchft_tpu.telemetry.critical_path import CriticalPathMonitor
        from torchft_tpu.telemetry.regression import (
            RegressionDetector,
            RegressionMonitor,
        )

        lh, client = lighthouse
        rm = RegressionMonitor(
            lh.address(),
            detector=RegressionDetector(min_n=6, k=3),
            poll_s=0.05,
        )
        cpm = CriticalPathMonitor(lh.address())
        events = []
        for step in range(36):
            slow = step >= 18
            for rid, base in (("gA", 0.1), ("gB", 0.1)):
                local = base + (0.15 if (slow and rid == "gB") else 0.0)
                _feed(client, rid, step, {
                    "local_s": local,
                    "wall_s": local + 0.05,
                    "phase.compute": local,
                })
            events.extend(rm.poll_once())
            cpm.poll_once()
        cpm.drain()
        latched = [e for e in events if e["event"] == "perf_regression"]
        assert latched and all(e["replica"] == "gB" for e in latched)
        # within a few observations of the onset at step 18
        assert min(e["step"] for e in latched) <= 28
        blame = cpm.attributor.blame_by_replica()
        assert blame.get("gB", 0) > 0.8 * sum(blame.values())
        rep = cpm.attributor.report()
        assert rep["whatif_steps_per_sec"] > rep["measured_steps_per_sec"]

    def test_monitor_survives_unreachable_lighthouse(self):
        from torchft_tpu.telemetry.regression import RegressionMonitor

        rm = RegressionMonitor("http://127.0.0.1:9", poll_s=0.05)
        assert rm.poll_once() == []  # degrades, never raises


# ---------------------------------------------------------------------------
# postmortem --perf window mode
# ---------------------------------------------------------------------------


class TestPostmortemPerf:
    def test_perf_windows_from_black_boxes(self, tmp_path, monkeypatch):
        from torchft_tpu.telemetry.blackbox import BlackBox
        from torchft_tpu.telemetry.postmortem import (
            perf_windows,
            render_perf_text,
        )

        box = BlackBox(path=str(tmp_path / "tft_bb_91001.bb"))
        box.set_context(replica_id="gShift", step=0, quorum_epoch=1)
        for step in range(1, 30):
            local = 4.0 if step == 1 else (0.1 if step < 18 else 0.3)
            box.record(
                "anatomy_tick", step=step,
                wall_s=local + 0.02, local_s=local,
            )
        box.close()
        rep = perf_windows(str(tmp_path), min_n=6)
        info = rep["replicas"]["gShift"]
        assert info["steps"] == 29
        latched = [
            e for e in info["shifts"] if e["event"] == "perf_regression"
        ]
        assert latched, rep
        assert all(e["replica"] == "gShift" for e in latched)
        assert info["local_tail_mean_s"] > info["local_head_mean_s"] or \
            latched  # the shift is visible one way or the other
        text = render_perf_text(rep)
        assert "gShift" in text and "perf_regression" in text

    def test_perf_cli(self, tmp_path):
        from torchft_tpu.telemetry.blackbox import BlackBox
        from torchft_tpu.telemetry import postmortem

        box = BlackBox(path=str(tmp_path / "tft_bb_91002.bb"))
        box.set_context(replica_id="gA", step=0, quorum_epoch=1)
        for step in range(1, 10):
            box.record(
                "anatomy_tick", step=step, wall_s=0.1, local_s=0.09
            )
        box.close()
        rc = postmortem.main([str(tmp_path), "--perf", "--window", "5"])
        assert rc == 0


# ---------------------------------------------------------------------------
# faultinject `after` onset rule
# ---------------------------------------------------------------------------


class TestAfterRule:
    def test_after_fires_from_onset_onward(self):
        from torchft_tpu.faultinject.core import FaultPlane

        plane = FaultPlane({
            "seed": 1,
            "rules": [{
                "site": "collective.issue", "match": "allreduce",
                "after": 4, "action": "delay", "ms": 1,
            }],
        })
        fired = [
            plane.hit("collective.issue", "allreduce", {}) is not None
            for _ in range(8)
        ]
        assert fired == [False] * 3 + [True] * 5

    def test_after_exclusive_with_nth(self):
        from torchft_tpu.faultinject.core import FaultPlane

        with pytest.raises(ValueError, match="at most one"):
            FaultPlane({
                "rules": [{
                    "site": "rpc.send", "nth": 2, "after": 3,
                    "action": "delay", "ms": 1,
                }],
            })
