"""Replay the checker-compiled fault schedules through the real runner
(ISSUE 20 tentpole part 3 acceptance).

The shipped ``torchft_tpu/faultinject/compiled/*.json`` descriptors —
lowered from sampled coverage paths of the ``sync-2g`` model by
``analysis/protocol/compile.py`` — must run green through the actual
faultmatrix tier: the injected site fires (evidence record), the victim
dies and respawns, the survivors converge, final checksums are
bit-identical, and the conformance replay of the produced trails is
clean. Slow-marked: three full multi-process scenarios (~2 min); tier-1
covers the fast half (descriptor pinning, lowering unit tests, the
in-process round trip) in ``test_protocol.py``.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))


def test_compiled_schedules_replay_green(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "torchft_tpu.faultinject.runner",
         "--compiled", "--outdir", str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fault matrix clean" in proc.stdout
    with open(tmp_path / "faultmatrix.json", encoding="utf-8") as f:
        report = json.load(f)
    by_name = {r["scenario"]: r for r in report["results"]}
    expected = {"compiled_kill_quorum_reply", "compiled_kill_commit_vote",
                "compiled_kill_next_collective"}
    assert expected <= set(by_name), sorted(by_name)
    for name in expected:
        res = by_name[name]
        assert res["status"] == "passed", res
        # the compiled site fired, the victim died and respawned
        assert res["fired"] >= 1 and res["respawns"] >= 1, res
