"""Chaos soak: the same replica group SIGKILLed and restarted repeatedly
mid-training; the cohort must keep making progress and end bit-identical.

This is the real-subprocess escalation of the reference's torchelastic
restart emulation (manager_integ_test.py attempts=3, in-thread): three
full process kills, disk resume + live heal each time, no step skipped or
double-trained (trace-verified like tests/test_data_example.py)."""

import json
import os
import re
import signal
import subprocess
import sys
import time
import pytest

import numpy as np

from torchft_tpu.coordination import LighthouseServer

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
from conftest import scaled_timeout

pytestmark = pytest.mark.soak

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")

STEPS = 36
BATCH = 8
KILLS = 3


def _spawn(gid, lighthouse_addr, tmp):
    env = dict(os.environ)
    env.update(
        REPLICA_GROUP_ID=str(gid),
        NUM_REPLICA_GROUPS="2",
        STEPS=str(STEPS),
        BATCH=str(BATCH),
        DATA_PATH=os.path.join(tmp, "corpus.bin"),
        TRACE_PATH=os.path.join(tmp, f"trace{gid}.jsonl"),
        CKPT_DIR=os.path.join(tmp, "ckpt"),
        CKPT_EVERY="2",
        TORCHFT_LIGHTHOUSE=lighthouse_addr,
        JAX_PLATFORMS="cpu",
    )
    return subprocess.Popen(
        [sys.executable, os.path.join(_EXAMPLES, "train_bytes.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _trace_steps(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line)["step"] for line in f if line.strip()]


def test_repeated_kill_restart_converges(tmp_path):
    tmp = str(tmp_path)
    rng = np.random.default_rng(0)
    with open(os.path.join(tmp, "corpus.bin"), "wb") as f:
        f.write(rng.integers(0, 256, 4001, dtype=np.uint8).tobytes())

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {0: _spawn(0, addr, tmp), 1: _spawn(1, addr, tmp)}
    victim_trace = os.path.join(tmp, "trace1.jsonl")
    try:
        for round_i in range(KILLS):
            # wait until the victim has committed a few more steps
            target = len(_trace_steps(victim_trace)) + 3
            deadline = time.time() + 240
            while len(_trace_steps(victim_trace)) < target:
                if procs[1].poll() is not None or procs[0].poll() is not None:
                    break  # someone finished early (tiny run): stop killing
                assert time.time() < deadline, f"no progress in round {round_i}"
                time.sleep(0.5)
            if procs[1].poll() is not None:
                break
            os.kill(procs[1].pid, signal.SIGKILL)
            procs[1].wait()
            procs[1] = _spawn(1, addr, tmp)

        outs = {}
        for g in (0, 1):
            out, _ = procs[g].communicate(timeout=scaled_timeout(300))
            assert procs[g].returncode == 0, out.decode()[-2000:]
            outs[g] = out.decode()
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()

    sums = [
        re.search(r"param_checksum=(-?\d+\.\d+)", outs[g]).group(1)
        for g in (0, 1)
    ]
    assert sums[0] == sums[1], sums

    # the survivor committed every step exactly once; the victim never
    # double-trained (steps strictly increasing across all restarts)
    g0 = _trace_steps(os.path.join(tmp, "trace0.jsonl"))
    assert g0 == sorted(set(g0)) and set(g0) == set(range(STEPS))
    g1 = _trace_steps(victim_trace)
    assert g1 == sorted(set(g1)), "victim double-trained a step"
