"""Chaos soak: the same replica group SIGKILLed and restarted repeatedly
mid-training; the cohort must keep making progress and end bit-identical.

This is the real-subprocess escalation of the reference's torchelastic
restart emulation (manager_integ_test.py attempts=3, in-thread): three
full process kills, disk resume + live heal each time, no step skipped or
double-trained (trace-verified like tests/test_data_example.py).

These soaks race wall clocks; for DETERMINISTIC failure placement (kill a
peer mid-allreduce on a chosen plane, tear a CMA pull at a chosen byte,
delay a chosen commit vote) use the seeded fault-injection plane instead:
``torchft_tpu/faultinject/`` + ``pytest -m faultmatrix`` +
``python -m torchft_tpu.faultinject.runner`` — see
``docs/fault_injection.md``."""

import json
import os
import re
import signal
import subprocess
import sys
import time
import pytest

import numpy as np

from torchft_tpu.coordination import LighthouseServer

# multi-process soak tier: excluded from the default run (pyproject
# addopts); execute with `pytest -m soak`
from conftest import scaled_timeout

pytestmark = pytest.mark.soak

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")

STEPS = 36
BATCH = 8
KILLS = 3


# host-plane chaos matrix (round-4 review #10): the same randomized
# kill/restart schedule on every transport the host plane can select —
# CMA pulls (default on one host), the striped C++ TCP ring, and the
# pure-python ring fallback. The device plane (in-process) and the
# device-dist cohort-respawn path get their own soaks below.
_PLANES = {
    "native-cma": {},
    "native-tcp": {"TORCHFT_DP_CMA": "0"},
    "python-ring": {"TORCHFT_NATIVE_PLANE": "0"},
}


def _spawn(gid, lighthouse_addr, tmp, plane_env=None):
    env = dict(os.environ)
    env.update(
        REPLICA_GROUP_ID=str(gid),
        NUM_REPLICA_GROUPS="2",
        STEPS=str(STEPS),
        BATCH=str(BATCH),
        DATA_PATH=os.path.join(tmp, "corpus.bin"),
        TRACE_PATH=os.path.join(tmp, f"trace{gid}.jsonl"),
        CKPT_DIR=os.path.join(tmp, "ckpt"),
        CKPT_EVERY="2",
        TORCHFT_LIGHTHOUSE=lighthouse_addr,
        JAX_PLATFORMS="cpu",
    )
    env.update(plane_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(_EXAMPLES, "train_bytes.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _trace_steps(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line)["step"] for line in f if line.strip()]


@pytest.mark.parametrize("plane", sorted(_PLANES))
def test_repeated_kill_restart_converges(tmp_path, plane):
    tmp = str(tmp_path)
    rng = np.random.default_rng(0)
    with open(os.path.join(tmp, "corpus.bin"), "wb") as f:
        f.write(rng.integers(0, 256, 4001, dtype=np.uint8).tobytes())

    plane_env = _PLANES[plane]
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {
        0: _spawn(0, addr, tmp, plane_env),
        1: _spawn(1, addr, tmp, plane_env),
    }
    victim_trace = os.path.join(tmp, "trace1.jsonl")
    try:
        for round_i in range(KILLS):
            # wait until the victim has committed a few more steps
            target = len(_trace_steps(victim_trace)) + 3
            deadline = time.time() + 240
            while len(_trace_steps(victim_trace)) < target:
                if procs[1].poll() is not None or procs[0].poll() is not None:
                    break  # someone finished early (tiny run): stop killing
                assert time.time() < deadline, f"no progress in round {round_i}"
                time.sleep(0.5)
            if procs[1].poll() is not None:
                break
            os.kill(procs[1].pid, signal.SIGKILL)
            procs[1].wait()
            procs[1] = _spawn(1, addr, tmp, plane_env)

        outs = {}
        for g in (0, 1):
            out, _ = procs[g].communicate(timeout=scaled_timeout(300))
            assert procs[g].returncode == 0, out.decode()[-2000:]
            outs[g] = out.decode()
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()

    sums = [
        re.search(r"param_checksum=(-?\d+\.\d+)", outs[g]).group(1)
        for g in (0, 1)
    ]
    assert sums[0] == sums[1], sums

    # the survivor committed every step exactly once; the victim never
    # double-trained (steps strictly increasing across all restarts)
    g0 = _trace_steps(os.path.join(tmp, "trace0.jsonl"))
    assert g0 == sorted(set(g0)) and set(g0) == set(range(STEPS))
    g1 = _trace_steps(victim_trace)
    assert g1 == sorted(set(g1)), "victim double-trained a step"


def test_chaos_device_plane_random_failures():
    """The device plane's chaos soak: 2 in-process groups over the 'ft'
    psum (virtual CPU mesh) with a RANDOMIZED failure schedule — a
    SIGKILL has no in-process analogue, so failures are injected
    exceptions + torchelastic-style restart, the reference's own chaos
    model (manager_integ_test.py). Both groups must end bit-identical
    and every scheduled failure must actually have fired."""
    from test_integration import (
        FailureInjector,
        _run_groups,
        assert_rank_states_equal,
    )

    rng = np.random.default_rng(1234)
    total_steps = 10
    # 2 random failures on each group at distinct steps (never the same
    # step on both groups at once: that would lose the step entirely,
    # which is the min_replicas=2 outage case, not the chaos case)
    steps_g1 = sorted(
        int(s) for s in rng.choice(range(1, total_steps - 1), 2, replace=False)
    )
    remaining = [s for s in range(1, total_steps - 1) if s not in steps_g1]
    steps_g0 = sorted(
        int(s) for s in rng.choice(remaining, 2, replace=False)
    )
    injectors = [FailureInjector(), FailureInjector()]
    for s in steps_g0:
        injectors[0].fail_at(0, int(s))
    for s in steps_g1:
        injectors[1].fail_at(0, int(s))

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        results = _run_groups(
            lighthouse,
            injectors,
            train_loop_args={"device_plane": True, "total_steps": total_steps},
        )
    finally:
        lighthouse.shutdown()
    assert_rank_states_equal(results)
    assert injectors[0].count == 2 and injectors[1].count == 2
    assert all(r["step"] >= total_steps for group in results for r in group)


_CHAOS_DD_WORKER = r"""
import logging, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "__REPO__")
import json
from datetime import timedelta
import numpy as np
import optax
from torchft_tpu.checkpointing.collectives_transport import CollectivesTransport
from torchft_tpu.checkpointing.disk import DiskCheckpointer
from torchft_tpu.collectives_device_dist import CollectivesDeviceDist, init_from_env
from torchft_tpu.manager import Manager
from torchft_tpu.optim import ManagedOptimizer
from torchft_tpu.store import StoreServer

workdir = sys.argv[1]
gid = int(os.environ["REPLICA_GROUP_ID"])
logging.basicConfig(
    level=logging.INFO,
    filename=os.path.join(workdir, f"g{gid}.log"),
)
STEPS = 14
assert init_from_env(), "cohort env missing"
collectives = CollectivesDeviceDist(timeout=timedelta(seconds=30))
store = StoreServer()
manager = Manager(
    collectives=collectives,
    load_state_dict=None,
    state_dict=None,
    min_replica_size=2,
    replica_id=f"chaos_dd_{gid}",
    store_addr=store.address(),
    rank=0,
    world_size=1,
    timeout=timedelta(seconds=30),
    checkpoint_transport=CollectivesTransport(
        collectives, timeout=timedelta(seconds=30)
    ),
)
rng = np.random.default_rng(7)
x = rng.standard_normal((256, 16)).astype(np.float32)
y = (x.sum(axis=1) > 0).astype(np.int32)

def loss_fn(params, xb, yb):
    logits = xb @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

opt = ManagedOptimizer(manager, optax.adam(1e-2))
opt.init({
    "w": np.zeros((16, 2), np.float32),
    "b": np.zeros(2, np.float32),
})
# BOTH groups persist: either can be the stale one after a respawn
ckpt = DiskCheckpointer(
    os.path.join(workdir, f"ckpt{gid}"),
    manager,
    state_dict=lambda: {"opt": opt.state_dict()},
    load_state_dict=lambda s: opt.load_state_dict(s["opt"]),
    every=3,
    tag=f"group{gid}",
    is_writer=True,
)
ckpt.restore()
# randomized cohort-kill schedule: incarnation k kills group k%2 at a
# seeded random step, two kills total, third incarnation runs clean
death_file = os.path.join(workdir, "deaths.txt")
deaths = 0
if os.path.exists(death_file):
    deaths = len(open(death_file).read().splitlines())
die_step = None
if deaths < 2 and gid == deaths % 2:
    die_step = int(np.random.default_rng(100 + deaths).integers(4, 10))
value_and_grad = jax.jit(jax.value_and_grad(loss_fn))
import time
prev = manager.current_step()
while manager.current_step() < STEPS:
    idx = rng.integers(0, len(x), 32)
    opt.begin_step()
    loss, grads = value_and_grad(opt.params, x[idx], y[idx])
    opt.step(grads)
    if manager.current_step() == prev:
        time.sleep(0.2)
    prev = manager.current_step()
    ckpt.maybe_save()
    if die_step is not None and manager.current_step() >= die_step:
        with open(death_file, "a") as f:
            f.write(f"g{gid}@{manager.current_step()}\n")
        os._exit(1)
checksum = float(
    sum(float(np.asarray(v).sum()) for v in opt.params.values())
)
with open(os.path.join(workdir, f"g{gid}.json"), "w") as f:
    json.dump({"step": manager.current_step(), "checksum": checksum}, f)
manager.shutdown(wait=False)
store.shutdown()
"""


def test_chaos_device_dist_cohort_respawn(tmp_path):
    """Device-dist chaos: randomized kills of ALTERNATING cohort members
    under --shared-runtime semantics. Each kill forces a whole-cohort
    respawn (static multi-controller membership); the staler group heals
    live over the plane's CollectivesTransport each time; the run must
    finish with bit-identical params after 2 kills."""
    from torchft_tpu.launcher import launch_shared_runtime

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_CHAOS_DD_WORKER.replace("__REPO__", REPO))
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    env_save = dict(os.environ)
    os.environ["TORCHFT_LIGHTHOUSE"] = lighthouse.address()
    try:
        rc = launch_shared_runtime(
            [sys.executable, str(worker), str(tmp_path)],
            num_groups=2,
            max_restarts=3,
        )
    finally:
        os.environ.clear()
        os.environ.update(env_save)
        lighthouse.shutdown()
    assert rc == 0
    deaths = (tmp_path / "deaths.txt").read_text().splitlines()
    assert len(deaths) == 2, deaths
    # both victims were exercised (alternating schedule)
    assert {d.split("@")[0] for d in deaths} == {"g0", "g1"}, deaths
    r0, r1 = (
        json.load(open(tmp_path / f"g{g}.json")) for g in range(2)
    )
    assert r0["step"] == 14 and r1["step"] == 14, (r0, r1)
    assert r0["checksum"] == r1["checksum"], (r0, r1)
